"""Phase 1.5 — cross-process message-flow facts (docs/ANALYSIS.md).

The serve/HA/pool/distributor tiers speak a hand-rolled JSON-RPC dialect:
``{"cmd": ...}`` dicts framed by ``protocol.send_frame`` and dispatched
through ``if cmd == "..."`` chains against the closed command registries
(``SERVE_COMMANDS``, ``protocol.COMMANDS``, ``SHIP_COMMANDS``).  The name
registries are policed by R004-style rules; this module distills the
*schemas* — who sends what keys, who reads them, what comes back — so
R016 (schema drift) and R018 (chaos coverage) can check both sides.

Built lazily from the phase-1 ``summaries.Program`` (the already-parsed
trees — no new parses; the one-parse-per-file economy is pinned by
tests/test_analysis.py) and cached on the Program, so R016 and R018
share one build.  Facts:

  * **send sites** — dict payloads carrying a ``"cmd"`` key handed to a
    framing call.  The framing *helpers* are discovered by fixpoint from
    the ``send_frame`` seed: any function that forwards one of its own
    parameters into a known helper's payload position is itself a helper
    (``client._rpc_ok -> rpc -> _rpc_one -> send_frame``), and a helper
    whose payload is ``dict(param, cmd="x")`` ADDS that cmd
    (``pool.stage_rpc``).  Payloads resolve through dict literals,
    ``dict(base, k=v)``, same-scope ``name = {...}`` assignment plus
    ``name["k"] = v`` mutation (``If``-guarded mutations become
    *conditional* keys), and one call-graph hop into a dict-returning
    builder.  Dict keys spelled as constants (``protocol.EPOCH_KEY``)
    resolve through module-level string constants.
  * **dispatch arms** — per dispatcher (``cmd = req.get("cmd")`` + an
    ``if cmd == "..."`` / ``if cmd in REGISTRY`` chain), the keys each
    arm reads from the request: ``req["k"]`` = required, ``.get`` =
    optional, followed up to three resolvable calls deep
    (``daemon._cmd_submit -> jobs.parse_spec``).  Registered cmds with
    no explicit test claim the dispatcher's trailing body (the worker's
    ``fetch`` fall-through).  A request escaping into an unresolvable
    callee marks the arm's reads OPEN.
  * **reply shapes** — the union of dict keys an arm can return,
    following resolvable reply builders (``jobs.structured_error``);
    any unresolvable return path marks the reply OPEN.

Everything is false-negative-leaning: OPEN facts disable the checks that
would need them, they never guess.  Like the whole analyzer this imports
none of the checked code.
"""

from __future__ import annotations

import ast
import dataclasses

from locust_tpu.analysis.core import call_name

# Keys owned by the wire/framing layer, never application schema: "cmd"
# itself, the replay-guard freshness stamps (protocol.send_frame adds
# them), the fencing epoch and the telemetry correlation stamp.  They are
# never "dead" at a send site and never "required" at an arm.
WIRE_META_KEYS = frozenset({"cmd", "_ts", "_nonce", "_epoch", "trace"})

# Reply keys any cmd can legitimately carry regardless of its arm: the
# transport error ladder ({"status","error"}), structured_error's
# "code", and the HA redirect/fencing decorations ("primary", "epoch").
GENERIC_REPLY_KEYS = frozenset({"status", "code", "error", "epoch", "primary"})

# Callees a request dict can be handed to without "reading" keys the
# analysis must then treat as unknown.
_BENIGN_CALLEES = frozenset({
    "dict", "len", "str", "repr", "bool", "int", "list", "tuple", "set",
    "sorted", "isinstance", "type", "id", "print", "dumps", "deepcopy",
    "copy", "format",
})

_MAX_DEPTH = 3

# Build accounting, mirroring core.parse_count(): the R016/R018 pair must
# share ONE RpcProgram per (scope, registries, seeds) — pinned in tests.
_build_count = 0


def build_count() -> int:
    return _build_count


def reset_build_count() -> None:
    global _build_count
    _build_count = 0


@dataclasses.dataclass
class Payload:
    """Resolved key set of one dict expression."""

    keys: set          # definitely present
    cond: set          # present on some paths (If-guarded subscript adds)
    open: bool = False  # unresolved parts (**kw, unknown base, var key)
    cmd: str | None = None
    from_param: str | None = None  # derives from this enclosing-fn param

    def all_keys(self) -> set:
        return self.keys | self.cond


def _merge(a: Payload, b: Payload) -> Payload:
    """Union of alternative shapes (multiple assignments / return paths)."""
    cmd = a.cmd if a.cmd == b.cmd else None
    return Payload(
        a.keys | b.keys, a.cond | b.cond,
        a.open or b.open or (a.cmd != b.cmd),
        cmd, a.from_param or b.from_param,
    )


@dataclasses.dataclass
class HelperEntry:
    """One discovered framing helper: calls to ``leaf`` carry the payload
    at positional index ``call_index`` (self excluded), and the helper
    applies ``adds_*`` to it before framing (``dict(req, cmd="...")``)."""

    leaf: str
    call_index: int
    fn: object | None        # FunctionSummary; None for the seed
    adds_cmd: str | None
    adds_keys: frozenset
    adds_cond: frozenset
    chain: tuple             # forwarding-path FunctionSummaries (R018)


@dataclasses.dataclass
class SendSite:
    rel: str
    line: int
    col: int
    fn: object               # enclosing FunctionSummary
    cmd: str
    payload: Payload
    reply_reads: set
    fns: tuple               # enclosing fn + helper chain (R018 seeds)
    synthetic: bool = False  # emitted for a cmd-adding helper whose
    #                          call sites are statically unresolvable
    #                          (first-class dispatch through an executor)


@dataclasses.dataclass
class Arm:
    cmd: str
    rel: str
    line: int
    dispatcher: object       # FunctionSummary of the dispatch function
    required: set            # req["k"] reads (no default)
    optional: set            # req.get("k") / "k" in req reads
    open_reads: bool         # req escaped into an unresolvable callee
    reply_keys: set
    open_reply: bool
    fns: tuple               # dispatcher + resolved delegates (R018)


def _param_names(node) -> list:
    a = node.args
    return [p.arg for p in a.posonlyargs + a.args]


def _own_walk(node):
    """Subtree of ``node`` (a def or a statement list) excluding nested
    function bodies — each nested def is its own FunctionSummary, so
    scanning it here would double-count its sites/reads."""
    stack = list(node) if isinstance(node, list) else [node]
    first = not isinstance(node, list)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if not first:
                continue
        first = False
        yield n
        stack.extend(ast.iter_child_nodes(n))


_COND_ANCESTORS = (ast.If, ast.IfExp, ast.While, ast.For, ast.AsyncFor,
                   ast.Try, ast.ExceptHandler)


class RpcProgram:
    """The message-flow fact base R016/R018 run over."""

    def __init__(self, program, scope, registries, seeds):
        global _build_count
        _build_count += 1
        self.program = program
        self.scope = tuple(scope)
        self.mods = [
            m for m in program.modules.values()
            if m.rel.startswith(self.scope)
        ]
        # Command registries: module-level tuple-of-str constants, read
        # from the phase-1 trees (summaries.ModuleSummary.seq_consts).
        self.registry_cmds: dict[tuple, tuple] = {}
        for rel, var in registries:
            mod = program.by_module_rel.get(rel)
            cmds = mod.seq_consts.get(var) if mod is not None else None
            if cmds:
                self.registry_cmds[(rel, var)] = tuple(cmds)
        self.all_cmds = {
            c for cmds in self.registry_cmds.values() for c in cmds
        }
        self._parents: dict[int, dict] = {}
        self._returns_memo: dict[int, Payload] = {}
        self.helpers: dict[str, list[HelperEntry]] = {}
        self._helper_by_fn: dict[int, HelperEntry] = {}
        for leaf, idx in seeds:
            self.helpers.setdefault(leaf, []).append(
                HelperEntry(leaf, idx, None, None, frozenset(), frozenset(),
                            ())
            )
        self._fixpoint()
        self.sites: list[SendSite] = []
        self._collect_sites()
        self.arms: list[Arm] = []
        self._collect_arms()
        self.arm_index: dict[str, list[Arm]] = {}
        for a in self.arms:
            self.arm_index.setdefault(a.cmd, []).append(a)
        self.sites_by_cmd: dict[str, list[SendSite]] = {}
        for s in self.sites:
            self.sites_by_cmd.setdefault(s.cmd, []).append(s)

    # ------------------------------------------------------------ helpers

    def _fixpoint(self) -> None:
        for _ in range(12):
            changed = False
            for mod in self.mods:
                # module-level aliases: ``_rpc = rpc``
                for stmt in mod.sf.tree.body:
                    if (
                        isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Name)
                    ):
                        dst = stmt.targets[0].id
                        for e in self.helpers.get(stmt.value.id, []):
                            if (
                                e.fn is not None
                                and e.fn.module is mod
                                and all(x is not e
                                        for x in self.helpers.get(dst, []))
                            ):
                                self.helpers.setdefault(dst, []).append(e)
                                changed = True
                for fn in mod.functions:
                    if id(fn.node) in self._helper_by_fn:
                        continue
                    ent = self._helper_candidate(fn)
                    if ent is not None:
                        self.helpers.setdefault(ent.leaf, []).append(ent)
                        self._helper_by_fn[id(fn.node)] = ent
                        changed = True
            if not changed:
                break

    def _helper_candidate(self, fn) -> HelperEntry | None:
        params = _param_names(fn.node)
        for call in self._calls_in(fn):
            entry, arg = self._match_helper_call(fn, call)
            if entry is None or arg is None:
                continue
            p = self._payload_of(arg, fn, 0)
            if p is None or p.from_param is None or p.from_param not in params:
                continue
            idx = params.index(p.from_param)
            offset = 1 if params and params[0] in ("self", "cls") else 0
            if idx - offset < 0:
                continue
            return HelperEntry(
                fn.name, idx - offset, fn,
                p.cmd or entry.adds_cmd,
                frozenset(p.keys | set(entry.adds_keys)),
                frozenset(p.cond | set(entry.adds_cond)),
                (fn,) + entry.chain,
            )
        return None

    @staticmethod
    def _calls_in(fn):
        for n in _own_walk(fn.node):
            if isinstance(n, ast.Call):
                yield n

    def _match_helper_call(self, fn, call):
        name = call_name(call)
        leaf = name.split(".")[-1]
        entries = self.helpers.get(leaf)
        if not entries:
            return None, None
        for r in self.program.graph.resolve(fn.module, name,
                                            include_nested=True):
            e = self._helper_by_fn.get(id(r.node))
            if e is not None:
                return e, _arg_at(call, e.call_index)
        tried = set()
        for e in entries:
            if e.call_index in tried:
                continue
            tried.add(e.call_index)
            arg = _arg_at(call, e.call_index)
            if arg is None:
                continue
            p = self._payload_of(arg, fn, 0)
            if p is not None and (p.from_param or p.cmd or e.adds_cmd):
                return e, arg
        return None, None

    # ----------------------------------------------------------- payloads

    def _parents_of(self, fn) -> dict:
        cached = self._parents.get(id(fn.node))
        if cached is None:
            cached = {}
            for n in ast.walk(fn.node):
                for c in ast.iter_child_nodes(n):
                    cached[id(c)] = n
            self._parents[id(fn.node)] = cached
        return cached

    def _key_const(self, k, mod) -> str | None:
        """A dict key / subscript / .get argument as a string constant,
        resolving Name/Attribute spellings (protocol.EPOCH_KEY) through
        module-level string constants."""
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            return k.value
        if isinstance(k, ast.Name):
            return mod.str_consts.get(k.id)
        if isinstance(k, ast.Attribute) and isinstance(k.value, ast.Name):
            target = mod.imports.get(k.value.id)
            m = self.program.modules.get(target) if target else None
            return m.str_consts.get(k.attr) if m is not None else None
        return None

    def _payload_of(self, expr, fn, depth, active=frozenset()):
        """Key set of a dict-shaped expression, or None when the
        expression cannot be a dict we understand at all."""
        if depth > _MAX_DEPTH:
            return Payload(set(), set(), open=True)
        if isinstance(expr, ast.Dict):
            keys, cond, open_, cmd = set(), set(), False, None
            for k, v in zip(expr.keys, expr.values):
                name = self._key_const(k, fn.module) if k is not None else None
                if name is None:
                    open_ = True  # **base or unresolvable key
                    continue
                keys.add(name)
                if name == "cmd":
                    if isinstance(v, ast.Constant) and isinstance(v.value,
                                                                  str):
                        cmd = v.value
                    else:
                        open_ = True
            return Payload(keys, cond, open_, cmd)
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            if name.split(".")[-1] == "dict":
                base = Payload(set(), set())
                if expr.args:
                    b = self._payload_of(expr.args[0], fn, depth, active)
                    base = b if b is not None else Payload(set(), set(),
                                                           open=True)
                keys, open_, cmd = set(), base.open, base.cmd
                for kw in expr.keywords:
                    if kw.arg is None:
                        open_ = True
                        continue
                    keys.add(kw.arg)
                    if kw.arg == "cmd":
                        if isinstance(kw.value, ast.Constant) and isinstance(
                            kw.value.value, str
                        ):
                            cmd = kw.value.value
                        else:
                            open_ = True
                            cmd = None
                return Payload(base.keys | keys, set(base.cond), open_, cmd,
                               base.from_param)
            targets = self.program.graph.resolve(fn.module, name,
                                                 include_nested=True)
            if targets:
                merged = None
                for t in targets:
                    p = self._returns_payload(t, depth + 1)
                    merged = p if merged is None else _merge(merged, p)
                return merged
            return Payload(set(), set(), open=True)
        if isinstance(expr, ast.Name):
            return self._name_payload(expr.id, fn, depth, active)
        return Payload(set(), set(), open=True)

    def _name_payload(self, name, fn, depth, active):
        params = _param_names(fn.node)
        if name in active:
            # re-reference while resolving the same name: the value
            # before reassignment (``req = dict(req, cmd=...)``).
            if name in params:
                return Payload(set(), set(), from_param=name)
            return Payload(set(), set(), open=True)
        assigns = [
            n for n in _own_walk(fn.node)
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and n.targets[0].id == name
            )
            or (
                # ``req: dict = {...}`` — client.submit/invalidate
                isinstance(n, ast.AnnAssign)
                and isinstance(n.target, ast.Name)
                and n.target.id == name
                and n.value is not None
            )
        ]
        merged = None
        for a in assigns:
            p = self._payload_of(a.value, fn, depth + 1, active | {name})
            if p is not None:
                merged = p if merged is None else _merge(merged, p)
        if merged is None:
            if name in params:
                return Payload(set(), set(), from_param=name)
            return Payload(set(), set(), open=True)
        parents = self._parents_of(fn)
        for n in _own_walk(fn.node):
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Subscript)
                and isinstance(n.targets[0].value, ast.Name)
                and n.targets[0].value.id == name
            ):
                k = self._key_const(n.targets[0].slice, fn.module)
                if k is None:
                    merged.open = True
                    continue
                if self._conditional(n, fn, parents):
                    merged.cond.add(k)
                else:
                    merged.keys.add(k)
                if k == "cmd" and isinstance(n.value, ast.Constant) and \
                        isinstance(n.value.value, str):
                    merged.cmd = n.value.value
        return merged

    def _conditional(self, node, fn, parents) -> bool:
        n = parents.get(id(node))
        while n is not None and n is not fn.node:
            if isinstance(n, _COND_ANCESTORS):
                return True
            n = parents.get(id(n))
        return False

    def _returns_payload(self, t_fn, depth) -> Payload:
        key = id(t_fn.node)
        memo = self._returns_memo.get(key)
        if memo is not None:
            return memo
        self._returns_memo[key] = Payload(set(), set(), open=True)  # cycle
        merged = None
        for n in _own_walk(t_fn.node):
            if isinstance(n, ast.Return):
                if n.value is None:
                    p = Payload(set(), set(), open=True)
                else:
                    p = self._payload_of(n.value, t_fn, depth)
                    if p is None:
                        p = Payload(set(), set(), open=True)
                merged = p if merged is None else _merge(merged, p)
        if merged is None:
            merged = Payload(set(), set(), open=True)
        self._returns_memo[key] = merged
        return merged

    # -------------------------------------------------------- send sites

    def _collect_sites(self) -> None:
        seen = set()
        for mod in self.mods:
            for fn in mod.functions:
                for call in self._calls_in(fn):
                    entry, arg = self._match_helper_call(fn, call)
                    if entry is None:
                        continue
                    key = (mod.rel, call.lineno, call.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    p = (self._payload_of(arg, fn, 0)
                         if arg is not None else None)
                    if p is None:
                        p = Payload(set(), set(), open=True)
                    if p.from_param is not None:
                        continue  # helper-internal forwarding
                    cmd = p.cmd or entry.adds_cmd
                    if cmd is None:
                        continue  # replies / frames without a cmd
                    payload = Payload(
                        p.keys | set(entry.adds_keys),
                        p.cond | set(entry.adds_cond), p.open, cmd,
                    )
                    self.sites.append(SendSite(
                        mod.rel, call.lineno, call.col_offset, fn, cmd,
                        payload, self._reply_reads(call, fn),
                        (fn,) + entry.chain,
                    ))
        # A helper that ADDS a const cmd is itself the send surface for
        # that cmd when its callers are statically unresolvable (the
        # daemon hands ``_run_plan_stage_rpc`` to an executor as a
        # value).  Emit one OPEN site at the helper def: the fencing
        # check still sees its adds, and the required-read check knows
        # this cmd has senders it cannot enumerate.
        for entries in self.helpers.values():
            for e in entries:
                if e.fn is None or e.adds_cmd is None:
                    continue
                key = ("helper", id(e.fn.node))
                if key in seen:
                    continue
                seen.add(key)
                self.sites.append(SendSite(
                    e.fn.rel, e.fn.lineno, 0, e.fn, e.adds_cmd,
                    Payload(set(e.adds_keys), set(e.adds_cond), True,
                            e.adds_cmd),
                    set(), (e.fn,) + e.chain, synthetic=True,
                ))

    def _reply_reads(self, call, fn) -> set:
        parents = self._parents_of(fn)
        par = parents.get(id(call))
        reads: set = set()
        if (
            isinstance(par, ast.Assign)
            and len(par.targets) == 1
            and isinstance(par.targets[0], ast.Name)
        ):
            rname = par.targets[0].id
            for n in _own_walk(fn.node):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "get"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == rname
                    and n.args
                ):
                    k = self._key_const(n.args[0], fn.module)
                    if k:
                        reads.add(k)
                elif (
                    isinstance(n, ast.Subscript)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == rname
                    and isinstance(n.ctx, ast.Load)
                ):
                    k = self._key_const(n.slice, fn.module)
                    if k:
                        reads.add(k)
        elif isinstance(par, ast.Attribute) and par.attr == "get":
            gp = parents.get(id(par))
            if isinstance(gp, ast.Call) and gp.func is par and gp.args:
                k = self._key_const(gp.args[0], fn.module)
                if k:
                    reads.add(k)
        return reads

    # --------------------------------------------------- dispatcher arms

    def _collect_arms(self) -> None:
        for mod in self.mods:
            for fn in mod.functions:
                disp = self._dispatcher_of(fn)
                if disp is not None:
                    self._arms_of(fn, *disp)

    def _dispatcher_of(self, fn):
        params = set(_param_names(fn.node))
        for n in _own_walk(fn.node):
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)
                and isinstance(n.value.func, ast.Attribute)
                and n.value.func.attr == "get"
                and isinstance(n.value.func.value, ast.Name)
                and n.value.func.value.id in params
                and n.value.args
                and isinstance(n.value.args[0], ast.Constant)
                and n.value.args[0].value == "cmd"
            ):
                return n.targets[0].id, n.value.func.value.id
        return None

    def _registry_expr(self, expr, mod):
        if isinstance(expr, ast.Name):
            return mod.seq_consts.get(expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                          ast.Name):
            target = mod.imports.get(expr.value.id)
            m = self.program.modules.get(target) if target else None
            return m.seq_consts.get(expr.attr) if m is not None else None
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = []
            for e in expr.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.append(e.value)
                else:
                    return None
            return tuple(out)
        return None

    def _cmds_of_test(self, test, cmd_var, mod):
        """(explicit arm cmds, not-in gate registry or None)."""
        if isinstance(test, ast.BoolOp):
            cmds: set = set()
            gate = None
            for v in test.values:
                c, g = self._cmds_of_test(v, cmd_var, mod)
                cmds |= c
                gate = gate or g
            return cmds, gate
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return set(), None  # a negated cmd test is a gate, not an arm
        if (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id == cmd_var
            and len(test.ops) == 1
        ):
            op, comp = test.ops[0], test.comparators[0]
            if isinstance(op, ast.Eq):
                if isinstance(comp, ast.Constant) and isinstance(comp.value,
                                                                 str):
                    return {comp.value}, None
            elif isinstance(op, ast.In):
                reg = self._registry_expr(comp, mod)
                if reg:
                    return set(reg), None
            elif isinstance(op, ast.NotIn):
                reg = self._registry_expr(comp, mod)
                if reg:
                    return set(), tuple(reg)
        return set(), None

    def _arms_of(self, fn, cmd_var, req_param) -> None:
        mod = fn.module
        arm_specs = []
        gate_registry = None
        for n in _own_walk(fn.node):
            if not isinstance(n, ast.If):
                continue
            cmds, gate = self._cmds_of_test(n.test, cmd_var, mod)
            if gate is not None and gate_registry is None:
                gate_registry = gate
            if cmds:
                arm_specs.append((cmds, n))
        explicit: set = set()
        for cmds, _ in arm_specs:
            explicit |= cmds
        body = list(fn.node.body)
        arm_if_ids = {id(n) for _, n in arm_specs}
        last = -1
        for i, stmt in enumerate(body):
            if id(stmt) in arm_if_ids:
                last = i
        trailing_body = body[last + 1:] if last >= 0 else []
        trailing_cmds = set(gate_registry or ()) - explicit

        banned: set = set()
        for _, n in arm_specs:
            for b in n.body:
                banned.update(id(x) for x in ast.walk(b))
        for stmt in trailing_body:
            banned.update(id(x) for x in ast.walk(stmt))
        common = self._reads_of_body(body, fn, req_param, banned=banned)

        for cmds, n in arm_specs:
            r = self._reads_of_body(n.body, fn, req_param)
            reply_keys, open_reply = self._reply_of_body(n.body, fn)
            for c in sorted(cmds):
                self.arms.append(Arm(
                    c, mod.rel, n.lineno, fn,
                    set(r.required),
                    set(r.optional) | set(common.required)
                    | set(common.optional),
                    r.open or common.open, reply_keys, open_reply,
                    (fn,) + tuple(r.fns),
                ))
        if trailing_cmds:
            if trailing_body:
                r = self._reads_of_body(trailing_body, fn, req_param)
                reply_keys, open_reply = self._reply_of_body(trailing_body,
                                                             fn)
            else:
                r = _Reads()
                r.open = True
                reply_keys, open_reply = set(), True
            line = trailing_body[0].lineno if trailing_body else fn.lineno
            for c in sorted(trailing_cmds):
                self.arms.append(Arm(
                    c, mod.rel, line, fn,
                    set(r.required),
                    set(r.optional) | set(common.required)
                    | set(common.optional),
                    r.open or common.open, reply_keys, open_reply,
                    (fn,) + tuple(r.fns),
                ))

    def _reads_of_body(self, stmts, fn, param, depth=0, visited=None,
                       banned=None):
        r = _Reads()
        if visited is None:
            visited = set()
        vkey = (id(fn.node), param)
        if vkey in visited or depth > _MAX_DEPTH:
            r.open = depth > _MAX_DEPTH
            return r
        visited.add(vkey)
        for n in _own_walk(stmts):
            if banned is not None and id(n) in banned:
                continue
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == param
            ):
                if n.func.attr == "get" and n.args:
                    k = self._key_const(n.args[0], fn.module)
                    if k is None:
                        r.open = True
                    else:
                        r.optional.add(k)
                elif n.func.attr in ("items", "keys", "values"):
                    r.open = True  # iterates every key
                continue
            if (
                isinstance(n, ast.Subscript)
                and isinstance(n.value, ast.Name)
                and n.value.id == param
                and isinstance(n.ctx, ast.Load)
            ):
                k = self._key_const(n.slice, fn.module)
                if k is None:
                    r.open = True
                else:
                    r.required.add(k)
                continue
            if (
                isinstance(n, ast.Compare)
                and len(n.ops) == 1
                and isinstance(n.ops[0], (ast.In, ast.NotIn))
                and isinstance(n.comparators[0], ast.Name)
                and n.comparators[0].id == param
            ):
                k = self._key_const(n.left, fn.module)
                if k is not None:
                    r.optional.add(k)
                continue
            if isinstance(n, ast.Call):
                self._follow_req(n, fn, param, r, depth, visited)
        return r

    def _follow_req(self, call, fn, param, r, depth, visited) -> None:
        """A call receiving the request dict: recurse into resolvable
        callees' reads; anything else opens the arm."""
        passed_at = [
            i for i, a in enumerate(call.args)
            if isinstance(a, ast.Name) and a.id == param
        ]
        passed_kw = any(
            isinstance(kw.value, ast.Name) and kw.value.id == param
            for kw in call.keywords
        )
        if not passed_at and not passed_kw:
            return
        name = call_name(call)
        if name.split(".")[-1] in _BENIGN_CALLEES:
            return
        if passed_kw:
            r.open = True
            return
        targets = self.program.graph.resolve(fn.module, name,
                                             include_nested=True)
        if not targets:
            r.open = True
            return
        for t in targets:
            tparams = _param_names(t.node)
            offset = (
                1 if tparams and tparams[0] in ("self", "cls")
                and isinstance(call.func, ast.Attribute) else 0
            )
            for i in passed_at:
                if i + offset >= len(tparams):
                    r.open = True
                    continue
                sub = self._reads_of_body(
                    list(t.node.body), t, tparams[i + offset],
                    depth + 1, visited,
                )
                r.required |= sub.required
                r.optional |= sub.optional
                r.open = r.open or sub.open
                r.fns.append(t)
                r.fns.extend(sub.fns)

    def _reply_of_body(self, stmts, fn):
        merged = None
        for n in _own_walk(stmts):
            if isinstance(n, ast.Return):
                if n.value is None:
                    p = Payload(set(), set(), open=True)
                else:
                    p = self._payload_of(n.value, fn, 1)
                    if p is None:
                        p = Payload(set(), set(), open=True)
                merged = p if merged is None else _merge(merged, p)
        if merged is None:
            return set(), True
        return merged.all_keys(), merged.open or merged.from_param is not None


class _Reads:
    def __init__(self):
        self.required: set = set()
        self.optional: set = set()
        self.open = False
        self.fns: list = []


def _arg_at(call, idx):
    if idx < len(call.args):
        a = call.args[idx]
        if isinstance(a, ast.Starred):
            return None
        return a
    return None


def get(program, scope, registries, seeds) -> RpcProgram:
    """The cached RpcProgram for this (scope, registries, seeds) — R016
    and R018 share one build per analysis run (pinned alongside the
    parse-once economy in tests/test_analysis.py)."""
    cache = program.__dict__.setdefault("_rpcflow_cache", {})
    key = (tuple(scope), tuple(registries), tuple(seeds))
    if key not in cache:
        cache[key] = RpcProgram(program, scope, registries, seeds)
    return cache[key]
