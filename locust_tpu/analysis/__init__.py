"""locust_tpu.analysis — AST-based invariant checker (tier-1 gate).

Static rules for the three invariant families this repo enforces by hand
(and has already paid debugging hours for): thread-shared state in the
distributor, purity of traced (jit/shard_map/Pallas) code, and closed
registries that drift silently (faultplan SITES vs docs, wire constants
vs serde).  Lockset spirit: Savage et al., "Eraser" (1997); fault-site
coverage spirit: Alvaro et al., lineage-driven fault injection (2015).

Usage::

    python -m locust_tpu.analysis [--json] [--rule R00x] [paths...]

Exit code 1 on NEW findings (not in the checked-in baseline).  Rules,
suppression syntax and the incident each rule encodes: docs/ANALYSIS.md.
Suppress one line with ``# locust: noqa[R00x] <reason>`` — the reason is
mandatory (an empty reason is itself a finding).
"""

from locust_tpu.analysis.core import (  # noqa: F401 - public API
    AnalysisResult,
    Finding,
    SourceFile,
    run_analysis,
)
from locust_tpu.analysis.registry import all_rules, get_rules  # noqa: F401
