"""R014/R015 — plan registry drift (two-sided, the R004/R011 mold).

The plan layer's whole extensibility story is ONE closed registry
(``locust_tpu/plan/nodes.py`` ``NODE_KINDS``): every dataflow node a
plan may use is an entry there, validation rejects anything else, and
``plan/compile.py`` must lower every entry (docs/PLAN.md).  ROADMAP
item 4's operators land as NEW KINDS in that registry — this rule keeps
both sides honest as they do:

  * every node-kind literal CONSTRUCTED or MATCHED under ``locust_tpu/``
    must be a registry entry — a typo'd kind at a construction site is a
    plan nothing can validate, and a matcher arm for an unregistered
    kind is dead code lying about coverage.  Recognized spellings (the
    convention ``plan/nodes.py`` establishes): ``node(id, "kind", ...)``
    / ``Node(kind="kind", ...)`` calls anywhere, and ``<expr>.kind ==
    "kind"`` / ``<expr>.kind in ("a", "b")`` comparisons inside
    ``locust_tpu/plan/`` (attribution discipline, like R005's
    int-in-wire-layer rule: ``.kind`` is a common attribute name —
    e.g. the analyzer's own thread summaries — so the comparison form
    only binds where the plan convention lives);
  * every registry entry must be LOWERED in ``plan/compile.py`` (its
    literal appears there), exercised under ``tests/`` (quoted), and
    documented in ``docs/PLAN.md`` (backticked) — a kind the compiler
    cannot lower is a validation-passes/dispatch-explodes trap, and an
    untested or undocumented kind is an unanchored contract;
  * every registry entry must be COVERED by the distributed planner:
    matched (``.kind`` comparison / constructed) in
    ``plan/distribute.py`` or explicitly listed in its ``SOLO_ONLY``
    registry — two-sided, so a new kind can never silently stay
    undistributed (the silent-solo-demotion bug class), and a stale
    ``SOLO_ONLY`` entry for a kind distribute.py now matches is flagged
    too.

R015 applies the same stance to the optimizer's ``REWRITE_RULES``
registry (``locust_tpu/plan/optimize.py``): every
``record_rewrite("rule")`` literal under ``locust_tpu/`` must be a
registry entry (a typo'd id already fails loudly at runtime — the
static half catches it before the firing path is ever reached), and
every entry must be APPLIED in ``plan/optimize.py`` (its literal
appears outside the registry tuple itself), exercised under ``tests/``
(quoted) and documented in ``docs/PLAN.md`` (backticked) — a
registered rewrite nothing fires, tests or documents is a byte-identity
claim nobody is checking.
"""

from __future__ import annotations

import ast
import os

from locust_tpu.analysis.core import Finding, Rule, call_name

PLAN_NODES_REL = "locust_tpu/plan/nodes.py"
PLAN_COMPILE_REL = "locust_tpu/plan/compile.py"
PLAN_DISTRIBUTE_REL = "locust_tpu/plan/distribute.py"
PLAN_DOCS_REL = "docs/PLAN.md"

_CTOR_NAMES = {"node", "Node"}


def _parse_str_tuple(files, root, rel, name):
    """A module-level ``NAME = ("a", "b", ...)`` tuple literal (plain or
    annotated assignment): {entry: line}, {} for an EMPTY tuple (a valid
    registry), None when the module or assignment is absent."""
    from locust_tpu.analysis.core import parse_registry_module

    tree = parse_registry_module(files, root, rel)
    if tree is None:
        return None
    for node in tree.body:
        value = None
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name
            for t in node.targets
        ):
            value = node.value
        elif (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == name
        ):
            value = node.value
        if isinstance(value, (ast.Tuple, ast.List)):
            out = {}
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    out[elt.value] = elt.lineno
            return out
    return None


def _parse_kinds(files, root, rel):
    """The NODE_KINDS tuple literal: {kind: line} (None when absent)."""
    return _parse_str_tuple(files, root, rel, "NODE_KINDS")


def _ctor_kind(call: ast.Call) -> str | None:
    """The kind literal of a ``node("id", "kind", ...)`` /
    ``Node(kind="kind", ...)`` construction, or None."""
    leaf = call_name(call).split(".")[-1]
    if leaf not in _CTOR_NAMES:
        return None
    for kw in call.keywords:
        if kw.arg == "kind" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    if len(call.args) >= 2:
        arg = call.args[1]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    return None


def _match_kinds(node: ast.Compare):
    """Kind literals of a ``<expr>.kind == "lit"`` / ``!=`` /
    ``in ("a", "b")`` comparison (empty list otherwise)."""
    left = node.left
    if not (isinstance(left, ast.Attribute) and left.attr == "kind"):
        return []
    if len(node.ops) != 1:
        return []
    cmp = node.comparators[0]
    if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
        if isinstance(cmp, ast.Constant) and isinstance(cmp.value, str):
            return [cmp.value]
    elif isinstance(node.ops[0], (ast.In, ast.NotIn)):
        if isinstance(cmp, (ast.Tuple, ast.List, ast.Set)):
            return [
                e.value for e in cmp.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
    return []


class PlanRegistryRule(Rule):
    rule_id = "R014"
    title = "plan NODE_KINDS registry drift"

    # Overridable for fixture trees in tests (the R004/R011 pattern).
    nodes_rel = PLAN_NODES_REL
    compile_rel = PLAN_COMPILE_REL
    distribute_rel = PLAN_DISTRIBUTE_REL
    docs_rel = PLAN_DOCS_REL
    analyzer_tests_rel = "tests/test_analysis.py"

    def check_project(self, files, root):
        kinds = _parse_kinds(files, root, self.nodes_rel)
        if kinds is None:
            yield Finding(
                self.rule_id, self.nodes_rel, 1, 0,
                "cannot parse the NODE_KINDS registry (module missing or "
                "no module-level `NODE_KINDS = (...)` tuple literal)",
            )
            return

        plan_prefix = os.path.dirname(self.nodes_rel) + "/"

        # Side 1: every constructed/matched kind literal is registered.
        # The same walk collects distribute.py's matched kinds as side
        # 3's coverage evidence (matcher arms + constructions there).
        compile_literals: set[str] = set()
        distribute_matched: set[str] = set()
        for sf in files:
            in_locust = sf.rel.split("/", 1)[0] == "locust_tpu" or \
                sf.rel.startswith(plan_prefix)
            if sf.rel == self.compile_rel:
                for node in ast.walk(sf.tree):
                    if isinstance(node, ast.Constant) and isinstance(
                        node.value, str
                    ):
                        compile_literals.add(node.value)
            if not in_locust or sf.rel == self.nodes_rel:
                # The registry module defines the kinds; re-reporting
                # its own literals would flag the registry itself.
                continue
            for node in ast.walk(sf.tree):
                found = []
                if isinstance(node, ast.Call):
                    k = _ctor_kind(node)
                    if k is not None:
                        found = [k]
                elif isinstance(node, ast.Compare) and sf.rel.startswith(
                    plan_prefix
                ):
                    found = _match_kinds(node)
                if found and sf.rel == self.distribute_rel:
                    distribute_matched.update(found)
                for k in found:
                    if k not in kinds:
                        yield Finding(
                            self.rule_id, sf.rel, node.lineno,
                            node.col_offset,
                            f"plan node kind {k!r} is not in "
                            f"NODE_KINDS ({self.nodes_rel}) — a typo'd "
                            "kind is a plan nothing can validate",
                        )

        def read(rel):
            try:
                with open(os.path.join(root, rel), encoding="utf-8") as f:
                    return f.read()
            except OSError:
                return None

        docs_text = read(self.docs_rel)
        # The analyzer's OWN suite is excluded from the exercised-scan:
        # its R014 fixtures quote phantom kinds ("window", ...) to test
        # the RULE, and counting those as coverage would let a real
        # future kind with that name pass the untested check forever.
        tests_text = "\n".join(
            sf.text for sf in files
            if sf.rel.split("/", 1)[0] == "tests"
            and sf.rel != self.analyzer_tests_rel
        )
        if docs_text is None:
            yield Finding(
                self.rule_id, self.docs_rel, 1, 0,
                f"plan docs {self.docs_rel} missing — NODE_KINDS entries "
                "cannot be verified as documented",
            )

        # Side 2: every registered kind is lowered, exercised, documented.
        for kind, line in sorted(kinds.items()):
            if kind not in compile_literals:
                yield Finding(
                    self.rule_id, self.nodes_rel, line, 0,
                    f"NODE_KINDS entry {kind!r} is never lowered in "
                    f"{self.compile_rel} — a kind validation admits but "
                    "the compiler cannot execute is a dispatch-time trap",
                )
            if f'"{kind}"' not in tests_text:
                yield Finding(
                    self.rule_id, self.nodes_rel, line, 0,
                    f"NODE_KINDS entry {kind!r} is never exercised under "
                    "tests/ — an untested node kind is an untested "
                    "dataflow contract",
                )
            if docs_text is not None and f"`{kind}`" not in docs_text:
                yield Finding(
                    self.rule_id, self.nodes_rel, line, 0,
                    f"NODE_KINDS entry {kind!r} is undocumented in "
                    f"{self.docs_rel} (backtick the kind in the node "
                    "catalog)",
                )

        # Side 3: distributed coverage, two-sided.  Every kind either
        # participates in a distributed shape (matched in
        # plan/distribute.py) or is explicitly distribution-exempt in
        # its SOLO_ONLY registry — and an exemption for a kind
        # distribute.py matches is stale and flagged.
        solo_only = _parse_str_tuple(
            files, root, self.distribute_rel, "SOLO_ONLY"
        )
        if solo_only is None:
            yield Finding(
                self.rule_id, self.distribute_rel, 1, 0,
                "cannot parse the SOLO_ONLY registry (module missing or "
                "no module-level `SOLO_ONLY = (...)` tuple literal) — "
                "distributed coverage of NODE_KINDS cannot be verified",
            )
            return
        for k, line in sorted(solo_only.items()):
            if k not in kinds:
                yield Finding(
                    self.rule_id, self.distribute_rel, line, 0,
                    f"SOLO_ONLY entry {k!r} is not a NODE_KINDS entry "
                    f"({self.nodes_rel}) — an exemption for a kind that "
                    "does not exist hides a typo",
                )
            elif k in distribute_matched:
                yield Finding(
                    self.rule_id, self.distribute_rel, line, 0,
                    f"SOLO_ONLY entry {k!r} is matched in "
                    f"{self.distribute_rel} — the exemption is stale; "
                    "drop it so the coverage claim stays honest",
                )
        for kind, line in sorted(kinds.items()):
            if kind not in distribute_matched and kind not in solo_only:
                yield Finding(
                    self.rule_id, self.nodes_rel, line, 0,
                    f"NODE_KINDS entry {kind!r} is neither matched in "
                    f"{self.distribute_rel} nor registered SOLO_ONLY "
                    "there — a new kind must either join a distributed "
                    "shape or declare itself solo-only, never silently "
                    "stay undistributed",
                )


PLAN_OPTIMIZE_REL = "locust_tpu/plan/optimize.py"


def _parse_rewrite_rules(files, root, rel):
    """The REWRITE_RULES tuple literal: ``({rule: line}, (lo, hi))``
    where (lo, hi) is the assignment's own line span (its literals are
    the registry, not applied-side evidence), or ``(None, None)``."""
    from locust_tpu.analysis.core import parse_registry_module

    tree = parse_registry_module(files, root, rel)
    if tree is None:
        return None, None
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "REWRITE_RULES"
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            rules = {}
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    rules[elt.value] = elt.lineno
            return rules, (node.lineno, node.end_lineno or node.lineno)
    return None, None


class RewriteRegistryRule(Rule):
    rule_id = "R015"
    title = "plan REWRITE_RULES registry drift"

    # Overridable for fixture trees in tests (the R004/R011/R014 pattern).
    optimize_rel = PLAN_OPTIMIZE_REL
    docs_rel = PLAN_DOCS_REL
    analyzer_tests_rel = "tests/test_analysis.py"

    def check_project(self, files, root):
        rules, span = _parse_rewrite_rules(files, root, self.optimize_rel)
        if rules is None:
            yield Finding(
                self.rule_id, self.optimize_rel, 1, 0,
                "cannot parse the REWRITE_RULES registry (module missing "
                "or no module-level `REWRITE_RULES = (...)` tuple "
                "literal)",
            )
            return

        # Side 1: every record_rewrite("lit") under locust_tpu/ is a
        # registry entry.  The optimize module's own string constants
        # OUTSIDE the registry assignment double as the applied-side
        # evidence for side 2 (exact whole-string match — docstrings
        # don't count, a rule id embedded in prose is not an
        # application site).
        applied_literals: set[str] = set()
        for sf in files:
            if sf.rel == self.optimize_rel:
                for node in ast.walk(sf.tree):
                    if (
                        isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and not (span[0] <= node.lineno <= span[1])
                    ):
                        applied_literals.add(node.value)
            if sf.rel.split("/", 1)[0] != "locust_tpu":
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                if call_name(node).split(".")[-1] != "record_rewrite":
                    continue
                if (
                    node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    r = node.args[0].value
                    if r not in rules:
                        yield Finding(
                            self.rule_id, sf.rel, node.lineno,
                            node.col_offset,
                            f"rewrite rule {r!r} is not in REWRITE_RULES "
                            f"({self.optimize_rel}) — an unregistered id "
                            "fails loudly at the firing site; register it",
                        )

        def read(rel):
            try:
                with open(os.path.join(root, rel), encoding="utf-8") as f:
                    return f.read()
            except OSError:
                return None

        docs_text = read(self.docs_rel)
        # Same exclusion as R014: the analyzer's own suite quotes
        # phantom rule ids to test the RULE — those are not coverage.
        tests_text = "\n".join(
            sf.text for sf in files
            if sf.rel.split("/", 1)[0] == "tests"
            and sf.rel != self.analyzer_tests_rel
        )
        if docs_text is None:
            yield Finding(
                self.rule_id, self.docs_rel, 1, 0,
                f"plan docs {self.docs_rel} missing — REWRITE_RULES "
                "entries cannot be verified as documented",
            )

        # Side 2: every registered rule is applied, exercised, documented.
        for rule, line in sorted(rules.items()):
            if rule not in applied_literals:
                yield Finding(
                    self.rule_id, self.optimize_rel, line, 0,
                    f"REWRITE_RULES entry {rule!r} is never applied in "
                    f"{self.optimize_rel} — a registered rewrite nothing "
                    "fires is a dead contract",
                )
            if f'"{rule}"' not in tests_text:
                yield Finding(
                    self.rule_id, self.optimize_rel, line, 0,
                    f"REWRITE_RULES entry {rule!r} is never exercised "
                    "under tests/ — an untested rewrite is an untested "
                    "byte-identity claim",
                )
            if docs_text is not None and f"`{rule}`" not in docs_text:
                yield Finding(
                    self.rule_id, self.optimize_rel, line, 0,
                    f"REWRITE_RULES entry {rule!r} is undocumented in "
                    f"{self.docs_rel} (backtick the rule in the "
                    "Optimizer section)",
                )
