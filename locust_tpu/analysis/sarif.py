"""SARIF 2.1.0 emission — findings as a standard static-analysis log.

One run, one tool (``locust-analysis``), one result per finding; the
content-addressed fingerprint (core._fingerprint) rides in
``partialFingerprints`` so SARIF consumers dedupe across line drift
exactly like the native baseline does, and ``baselineState`` carries the
new/baselined split.  The shape here is pinned by
tests/test_analysis.py::test_sarif_schema_shape — CI/PR annotators
consume this file without any new infrastructure (docs/ANALYSIS.md).
"""

from __future__ import annotations

import json

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


DEFAULT_HELP_URI = "docs/ANALYSIS.md#rule-catalog"


def _rule_entry(rid: str, val) -> dict:
    """One driver.rules entry.  ``val`` is either a bare title string
    (the legacy catalog shape, kept working) or a Rule class — classes
    contribute ``helpUri`` (the docs/ANALYSIS.md anchor, overridable via
    a ``help_uri`` class attr) and ``defaultConfiguration.level`` derived
    from the rule's ``severity``."""
    if isinstance(val, str):
        return {"id": rid, "shortDescription": {"text": val}}
    sev = getattr(val, "severity", "error")
    return {
        "id": rid,
        "shortDescription": {"text": val.title},
        "helpUri": getattr(val, "help_uri", DEFAULT_HELP_URI),
        "defaultConfiguration": {
            "level": "error" if sev == "error" else "warning",
        },
    }


def sarif_report(result, rule_catalog: dict) -> dict:
    """``AnalysisResult`` + {rule id: title-or-Rule-class} -> a SARIF
    log dict."""
    results = []
    for f in result.findings:
        results.append({
            "ruleId": f.rule_id,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": f.col + 1,
                    },
                },
            }],
            "partialFingerprints": {
                "locustFingerprint/v1": f.fingerprint,
            },
            "baselineState": "unchanged" if f.baselined else "new",
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "locust-analysis",
                    "informationUri": "docs/ANALYSIS.md",
                    "rules": [
                        _rule_entry(rid, val)
                        for rid, val in sorted(rule_catalog.items())
                    ],
                },
            },
            "results": results,
        }],
    }


def write_sarif(path: str, result, rule_catalog: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(sarif_report(result, rule_catalog), f, indent=2,
                  sort_keys=True)
        f.write("\n")
