"""R004/R005 — closed registries that must not drift.

R004 (fault-site consistency), in the spirit of lineage-driven fault
injection (Alvaro et al., 2015) and the two-sided CONFIG_AB_KINDS
readers: the ``faultplan.SITES`` registry, its hook call-sites, the
chaos suite and the docs must all agree —

  * every site string at a hook call-site (``faultplan.fire("x", ...)``,
    ``mangle``/``delay``/``damage_file``/``check_connect`` and the
    in-module ``_PLAN.fire``) must be a registered site;
  * every registered site must be HOOKED somewhere in ``locust_tpu/``
    (a registry entry with no call-site injects nothing, silently);
  * every registered site must appear in ``tests/test_faults.py`` (it is
    exercised) and in ``docs/FAULTS.md`` (it is documented).

R005 (wire-constant drift): protocol magic bytes, versions and size
bounds have ONE defining module; a re-spelled literal elsewhere is a
fork waiting to disagree (``MAX_FRAME`` as ``64 * 1024 * 1024``, the
``b"\\x00LB"`` magic, serde's ``b"LKVB"``).  Constant expressions are
folded (``core.const_int``).  Attribution discipline: magic BYTES match
everywhere (they are distinctive), but int values match only inside the
wire layer itself (``locust_tpu/distributor/``) — 8/32/64 MiB are round
numbers that legitimately recur as corpus/IO sizes elsewhere, and a
false wire-skew claim on a bench corpus size would teach people to
ignore the rule.
"""

from __future__ import annotations

import ast
import os

from locust_tpu.analysis.core import Finding, Rule, call_name, const_int

FAULTPLAN_REL = "locust_tpu/utils/faultplan.py"
FAULTS_TESTS_REL = "tests/test_faults.py"
FAULTS_DOCS_REL = "docs/FAULTS.md"

_HOOK_NAMES = {"fire", "mangle", "delay", "damage_file", "check_connect"}


def _parse_sites(files, root, rel) -> tuple[dict | None, int]:
    """The SITES dict literal from faultplan.py: {site: line} (+ def line).
    Reuses the phase-1 parse when faultplan is in the analyzed set (the
    one-parse-per-file economy)."""
    from locust_tpu.analysis.core import parse_registry_module

    tree = parse_registry_module(files, root, rel)
    if tree is None:
        return None, 0
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "SITES"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            sites = {}
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    sites[k.value] = k.lineno
            return sites, node.lineno
    return None, 0


class FaultSiteConsistencyRule(Rule):
    rule_id = "R004"
    title = "faultplan SITES registry drift"

    # Overridable for fixture trees in tests.
    faultplan_rel = FAULTPLAN_REL
    tests_rel = FAULTS_TESTS_REL
    docs_rel = FAULTS_DOCS_REL

    def check_project(self, files, root):
        sites, sites_line = _parse_sites(files, root, self.faultplan_rel)
        if sites is None:
            yield Finding(
                self.rule_id, self.faultplan_rel, 1, 0,
                "cannot parse the SITES registry (module missing or no "
                "module-level `SITES = {...}` dict literal)",
            )
            return

        # Side 1: hook call-site strings must be registered sites.
        hooked: set[str] = set()
        for sf in files:
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                func = node.func
                attr = func.attr if isinstance(func, ast.Attribute) else (
                    func.id if isinstance(func, ast.Name) else None
                )
                if attr not in _HOOK_NAMES:
                    continue
                arg0 = node.args[0]
                if not (
                    isinstance(arg0, ast.Constant)
                    and isinstance(arg0.value, str)
                ):
                    continue
                site = arg0.value
                if "." not in site:  # e.g. str.replace("a", ...) lookalikes
                    continue
                if site not in sites:
                    yield Finding(
                        self.rule_id, sf.rel, node.lineno, node.col_offset,
                        f"fault hook {call_name(node)}({site!r}, ...) uses "
                        "a site not in faultplan.SITES — a typo'd site "
                        "injects nothing, silently",
                    )
                elif sf.rel.split("/", 1)[0] == "locust_tpu":
                    hooked.add(site)

        # check_connect hardcodes rpc.connect inside faultplan itself;
        # callers of check_connect(host, port) exercise it without the
        # string, so count the site hooked if ANY call-site exists.
        if "rpc.connect" in sites and any(
            isinstance(node, ast.Call)
            and call_name(node).endswith("check_connect")
            for sf in files
            if sf.rel.split("/", 1)[0] == "locust_tpu"
            for node in ast.walk(sf.tree)
        ):
            hooked.add("rpc.connect")

        def read(rel):
            try:
                with open(os.path.join(root, rel), encoding="utf-8") as f:
                    return f.read()
            except OSError:
                return None

        tests_text = read(self.tests_rel)
        docs_text = read(self.docs_rel)

        # Side 2: every registered site is hooked, tested, documented.
        for site, line in sorted(sites.items()):
            if site not in hooked:
                yield Finding(
                    self.rule_id, self.faultplan_rel, line, 0,
                    f"SITES entry {site!r} has no hook call-site under "
                    "locust_tpu/ — a registered site that injects nothing",
                )
            if tests_text is None:
                yield Finding(
                    self.rule_id, self.tests_rel, 1, 0,
                    f"chaos suite {self.tests_rel} missing — SITES "
                    "entries cannot be verified as exercised",
                )
                tests_text = ""  # report the missing file once
            elif site not in tests_text:
                yield Finding(
                    self.rule_id, self.faultplan_rel, line, 0,
                    f"SITES entry {site!r} is never exercised in "
                    f"{self.tests_rel} — an untested fault site is an "
                    "untested recovery path",
                )
            if docs_text is None:
                yield Finding(
                    self.rule_id, self.docs_rel, 1, 0,
                    f"fault docs {self.docs_rel} missing — SITES entries "
                    "cannot be verified as documented",
                )
                docs_text = ""
            elif site not in docs_text:
                yield Finding(
                    self.rule_id, self.faultplan_rel, line, 0,
                    f"SITES entry {site!r} is undocumented in "
                    f"{self.docs_rel}",
                )


# name -> defining module (repo-relative).  Ints below _INT_FLOOR are too
# common to attribute; bytes magics always match exactly.
WIRE_CONSTANTS = {
    "MAX_FRAME": "locust_tpu/distributor/protocol.py",
    "FETCH_CHUNK": "locust_tpu/distributor/protocol.py",
    "FETCH_CHUNK_MAX": "locust_tpu/distributor/protocol.py",
    "BIN_MAGIC": "locust_tpu/distributor/protocol.py",
    "BIN_VERSION": "locust_tpu/distributor/protocol.py",
    "KVB_MAGIC": "locust_tpu/io/serde.py",
    "KVB_VERSION": "locust_tpu/io/serde.py",
}
_INT_FLOOR = 65536


def _defined_constants(files, root: str) -> dict:
    """{name: (value, definer_rel)} for each wire constant we can read."""
    from locust_tpu.analysis.core import parse_registry_module

    out = {}
    for name, rel in WIRE_CONSTANTS.items():
        tree = parse_registry_module(files, root, rel)
        if tree is None:
            continue
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            ):
                continue
            if (
                isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, bytes)
            ):
                out[name] = (node.value.value, rel)
            else:
                iv = const_int(node.value)
                if iv is not None:
                    out[name] = (iv, rel)
    return out


class WireConstantDriftRule(Rule):
    rule_id = "R005"
    title = "re-spelled wire constant"

    def check_project(self, files, root):
        consts = _defined_constants(files, root)
        by_bytes = {
            v: (n, rel) for n, (v, rel) in consts.items()
            if isinstance(v, bytes)
        }
        by_int = {
            v: (n, rel) for n, (v, rel) in consts.items()
            if isinstance(v, int) and v >= _INT_FLOOR
        }
        for sf in files:
            in_wire_layer = sf.rel.startswith("locust_tpu/distributor/")
            for node in ast.walk(sf.tree):
                hit = None
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, bytes
                ):
                    hit = by_bytes.get(node.value)
                elif in_wire_layer and isinstance(
                    node, (ast.Constant, ast.BinOp)
                ):
                    iv = const_int(node)
                    if iv is not None:
                        hit = by_int.get(iv)
                # A definer may spell ITS OWN constants — but not another
                # module's (protocol.py re-spelling serde's KVB_MAGIC is
                # exactly the cross-module skew this rule exists for).
                if hit is None or hit[1] == sf.rel:
                    continue
                name, definer = hit
                yield Finding(
                    self.rule_id, sf.rel, node.lineno, node.col_offset,
                    f"literal re-spells {name} (defined once in "
                    f"{definer}) — import it; a fork of a wire constant "
                    "is a protocol skew waiting to disagree",
                )
