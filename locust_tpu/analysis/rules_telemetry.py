"""R009 — telemetry name-registry hygiene (two-sided, like R004/R005).

The obs subsystem's span/event/metric names live in ONE closed dict
(``locust_tpu/obs/names.py`` ``NAMES``); the Tracer/Metrics validate
against it at runtime, but only on the ENABLED path — a typo'd name at a
call-site that nobody runs traced would record nothing, silently,
forever.  This rule closes the loop statically, both directions:

  * every literal name at an obs emission site — ``obs.span(...)``,
    ``obs.event(...)``, ``obs.metric_inc/metric_set/metric_observe(...)``
    — must exist in NAMES, with the kind the hook implies (a counter
    incremented as a histogram is the same drift one step subtler);
  * every registered name must be EMITTED somewhere under ``locust_tpu/``
    (a registry entry nothing emits is a timeline nobody can correlate —
    and a doc that lies).

Attribution discipline: only calls whose receiver is literally the
``obs`` module (``obs.span``/``....obs.event``) are claimed — a
``SpanTimer.span("load")`` or any other object's ``.event(...)`` must
never false-positive, which is also why the emission CONVENTION
(docs/OBSERVABILITY.md) is module-function calls with literal names.
"""

from __future__ import annotations

import ast

from locust_tpu.analysis.core import Finding, Rule, unparse

OBS_NAMES_REL = "locust_tpu/obs/names.py"

# hook attribute -> the registry kind it emits.
_EMIT_KINDS = {
    "span": "span",
    "event": "event",
    "metric_inc": "counter",
    "metric_set": "gauge",
    "metric_observe": "histogram",
}


def _parse_names(files, root, rel) -> tuple[dict | None, int]:
    """The NAMES dict literal from obs/names.py: {name: (kind, line)}.
    Reuses the phase-1 parse (one-parse-per-file economy)."""
    from locust_tpu.analysis.core import parse_registry_module

    tree = parse_registry_module(files, root, rel)
    if tree is None:
        return None, 0
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "NAMES"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            names = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    names[k.value] = (v.value, k.lineno)
            return names, node.lineno
    return None, 0


class TelemetryRegistryRule(Rule):
    rule_id = "R009"
    title = "obs telemetry name-registry drift"

    # Overridable for fixture trees in tests (same pattern as R004).
    names_rel = OBS_NAMES_REL

    def check_project(self, files, root):
        names, _ = _parse_names(files, root, self.names_rel)
        if names is None:
            yield Finding(
                self.rule_id, self.names_rel, 1, 0,
                "cannot parse the NAMES registry (module missing or no "
                "module-level `NAMES = {...}` dict literal)",
            )
            return

        emitted: set[str] = set()
        for sf in files:
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                kind = _EMIT_KINDS.get(func.attr)
                if kind is None:
                    continue
                base = unparse(func.value)
                if base != "obs" and not base.endswith(".obs"):
                    continue
                arg0 = node.args[0]
                if not (
                    isinstance(arg0, ast.Constant)
                    and isinstance(arg0.value, str)
                ):
                    # Dynamic names are the runtime validator's problem;
                    # the CONVENTION is literal names exactly so this
                    # rule sees everything (docs/OBSERVABILITY.md).
                    continue
                name = arg0.value
                if name not in names:
                    yield Finding(
                        self.rule_id, sf.rel, node.lineno, node.col_offset,
                        f"obs.{func.attr}({name!r}, ...) uses a name not "
                        "in the obs NAMES registry "
                        f"({self.names_rel}) — a typo'd telemetry name "
                        "records nothing the timeline can correlate",
                    )
                elif names[name][0] != kind:
                    yield Finding(
                        self.rule_id, sf.rel, node.lineno, node.col_offset,
                        f"obs.{func.attr} emits {name!r}, which the "
                        f"registry declares a {names[name][0]} (needs a "
                        f"{kind}) — kind drift between emitter and "
                        "registry",
                    )
                elif sf.rel.split("/", 1)[0] == "locust_tpu":
                    emitted.add(name)

        for name, (kind, line) in sorted(names.items()):
            if name not in emitted:
                yield Finding(
                    self.rule_id, self.names_rel, line, 0,
                    f"NAMES entry {name!r} ({kind}) is never emitted "
                    "under locust_tpu/ — a registered telemetry name "
                    "nothing records is documentation drift",
                )
