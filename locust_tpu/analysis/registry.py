"""The closed rule registry (R001–R018) — itself anti-drift-checked:
``get_rules`` rejects unknown ids loudly, and tests/test_analysis.py
pins that every registered rule has firing + silent fixture coverage."""

from __future__ import annotations

from locust_tpu.analysis.rules_consistency import (
    FaultSiteConsistencyRule,
    WireConstantDriftRule,
)
from locust_tpu.analysis.rules_hygiene import (
    BenchContractRule,
    SubprocessEnvRule,
    TrackedArtifactRule,
)
from locust_tpu.analysis.rules_plan import (
    PlanRegistryRule,
    RewriteRegistryRule,
)
from locust_tpu.analysis.rules_rpc import (
    ChaosCoverageRule,
    RpcSchemaRule,
    SilentThreadDeathRule,
)
from locust_tpu.analysis.rules_serve import ServeErrorRegistryRule
from locust_tpu.analysis.rules_telemetry import TelemetryRegistryRule
from locust_tpu.analysis.rules_threads import (
    ThreadLifecycleRule,
    ThreadSharedStateRule,
    UnboundedBlockingRule,
)
from locust_tpu.analysis.rules_traced import (
    DonationHygieneRule,
    HostSyncInLoopRule,
    TracedPurityRule,
)

_RULE_CLASSES = (
    ThreadSharedStateRule,      # R001 (interprocedural since the 2-phase engine)
    TracedPurityRule,           # R002 (follows traced bodies into callees)
    HostSyncInLoopRule,         # R003
    FaultSiteConsistencyRule,   # R004
    WireConstantDriftRule,      # R005
    SubprocessEnvRule,          # R006
    BenchContractRule,          # R007
    TrackedArtifactRule,        # R008
    TelemetryRegistryRule,      # R009
    DonationHygieneRule,        # R010
    ServeErrorRegistryRule,     # R011
    ThreadLifecycleRule,        # R012
    UnboundedBlockingRule,      # R013
    PlanRegistryRule,           # R014
    RewriteRegistryRule,        # R015
    RpcSchemaRule,              # R016 (rpcflow: two-sided schema conformance)
    SilentThreadDeathRule,      # R017 (thread death + silent swallows)
    ChaosCoverageRule,          # R018 (chaos coverage per rpc cmd)
)


def all_rules() -> dict[str, type]:
    return {cls.rule_id: cls for cls in _RULE_CLASSES}


def get_rules(ids=None) -> list:
    """Instantiate the selected rules (all by default).  Unknown ids are
    a loud error — a typo'd --rule must not silently check nothing (the
    same closed-registry stance as faultplan.SITES)."""
    table = all_rules()
    if ids is None:
        return [cls() for cls in table.values()]
    out = []
    for rid in ids:
        if rid not in table:
            raise ValueError(
                f"unknown rule {rid!r} (known: {', '.join(sorted(table))})"
            )
        out.append(table[rid]())
    return out
