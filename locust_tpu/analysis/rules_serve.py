"""R011 — serve error-code registry drift (two-sided, the R004/R009 mold).

The serve tier's whole error discipline is ONE closed registry
(``locust_tpu/serve/jobs.py`` ``ERROR_CODES``): a client observes either
a correct result or a structured error whose ``code`` is a registry
entry — never a silent wrong answer (docs/SERVING.md).  The daemon took
ten review rounds to converge on exactly which codes exist
(``shutting_down`` vs queue_full at teardown, ``result_too_large`` for
the MAX_FRAME reply path, ``unknown_job`` guarding the invalidate
wipe-everything fallthrough); this rule keeps that converged state from
drifting, both directions:

  * every code EMITTED in ``locust_tpu/serve/`` — a literal first
    argument to ``structured_error(...)`` or ``AdmitReject(...)``, or
    the ``ValueError("code\\n...")`` first-line convention parse_spec
    uses — must be a registry entry (``structured_error`` raises at
    runtime, but only on paths something actually runs);
  * every registry entry must be emitted somewhere in serve/, documented
    in ``docs/SERVING.md``, and exercised by a literal mention under
    ``tests/`` — an unemitted code is a lie in the client's switch
    table, an untested one is an untested failure contract.

Dynamic codes (``structured_error(e.code, ...)`` relays) are skipped:
the convention is literal codes at origin sites, relays forward them.
"""

from __future__ import annotations

import ast
import os
import re

from locust_tpu.analysis.core import Finding, Rule, call_name

JOBS_REL = "locust_tpu/serve/jobs.py"
SERVE_PREFIX = "locust_tpu/serve/"
SERVING_DOCS_REL = "docs/SERVING.md"

_CODE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_EMIT_CALLS = {"structured_error", "AdmitReject"}


def _parse_error_codes(files, root, rel):
    """The ERROR_CODES tuple literal: {code: line} (None when absent)."""
    from locust_tpu.analysis.core import parse_registry_module

    tree = parse_registry_module(files, root, rel)
    if tree is None:
        return None
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "ERROR_CODES"
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            codes = {}
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    codes[elt.value] = elt.lineno
            return codes
    return None


def _valueerror_code(call: ast.Call) -> str | None:
    """The ``ValueError("code\\nmessage")`` first-line convention: the
    literal prefix before the first newline, when it looks like a code.
    Covers plain strings and f-strings whose FIRST piece is the literal
    ``code\\n`` prefix (``f"bad_spec\\n{e}"``)."""
    if not call.args:
        return None
    arg = call.args[0]
    text = None
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        text = arg.value
    elif isinstance(arg, ast.JoinedStr) and arg.values:
        first = arg.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            text = first.value
    if text is None or "\n" not in text:
        return None
    prefix = text.split("\n", 1)[0]
    return prefix if _CODE_RE.match(prefix) else None


class ServeErrorRegistryRule(Rule):
    rule_id = "R011"
    title = "serve ERROR_CODES registry drift"

    # Overridable for fixture trees in tests (same pattern as R004/R009).
    jobs_rel = JOBS_REL
    serve_prefix = SERVE_PREFIX
    docs_rel = SERVING_DOCS_REL

    def check_project(self, files, root):
        codes = _parse_error_codes(files, root, self.jobs_rel)
        if codes is None:
            yield Finding(
                self.rule_id, self.jobs_rel, 1, 0,
                "cannot parse the ERROR_CODES registry (module missing or "
                "no module-level `ERROR_CODES = (...)` tuple literal)",
            )
            return

        # Side 1: every literal code at an emission site is registered.
        emitted: set[str] = set()
        for sf in files:
            if not sf.rel.startswith(self.serve_prefix):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                callee_leaf = call_name(node).split(".")[-1]
                code = None
                if callee_leaf in _EMIT_CALLS and node.args:
                    arg0 = node.args[0]
                    if isinstance(arg0, ast.Constant) and isinstance(
                        arg0.value, str
                    ):
                        code = arg0.value
                elif callee_leaf == "ValueError":
                    code = _valueerror_code(node)
                if code is None:
                    continue
                if code not in codes:
                    yield Finding(
                        self.rule_id, sf.rel, node.lineno, node.col_offset,
                        f"structured error code {code!r} is not in "
                        f"jobs.ERROR_CODES ({self.jobs_rel}) — a client "
                        "switching on the registry can never handle it",
                    )
                else:
                    emitted.add(code)

        def read(rel):
            try:
                with open(os.path.join(root, rel), encoding="utf-8") as f:
                    return f.read()
            except OSError:
                return None

        docs_text = read(self.docs_rel)
        tests_text = "\n".join(
            sf.text for sf in files if sf.rel.split("/", 1)[0] == "tests"
        )
        if docs_text is None:
            # ONE finding for the missing file — per-code "undocumented"
            # findings against it would be N reports of one root cause.
            yield Finding(
                self.rule_id, self.docs_rel, 1, 0,
                f"serve docs {self.docs_rel} missing — ERROR_CODES "
                "entries cannot be verified as documented",
            )

        # Side 2: every registered code is emitted, documented, exercised.
        for code, line in sorted(codes.items()):
            if code not in emitted:
                yield Finding(
                    self.rule_id, self.jobs_rel, line, 0,
                    f"ERROR_CODES entry {code!r} is never emitted under "
                    f"{self.serve_prefix} — a registered reason code "
                    "nothing can raise is a lie in the client's switch "
                    "table",
                )
            if docs_text is not None and code not in docs_text:
                yield Finding(
                    self.rule_id, self.jobs_rel, line, 0,
                    f"ERROR_CODES entry {code!r} is undocumented in "
                    f"{self.docs_rel}",
                )
            if code not in tests_text:
                yield Finding(
                    self.rule_id, self.jobs_rel, line, 0,
                    f"ERROR_CODES entry {code!r} is never exercised under "
                    "tests/ — an untested reason code is an untested "
                    "failure contract",
                )
