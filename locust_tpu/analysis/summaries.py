"""Phase 1 of the two-phase engine: whole-program effect summaries.

Every configured file is parsed exactly once (``core.load_files``); this
module walks those trees ONCE more and distills, per module and per
function, the facts the interprocedural rules re-run over in phase 2
(docs/ANALYSIS.md):

  * **writes** — ``self.*``/``global``/own-``nonlocal`` assignments, each
    tagged with whether a ``with <lock>:`` encloses it locally (the
    Eraser-style lockset fact R001 propagates through call chains);
  * **calls** — every call with its dotted callee text and the same
    local lock context (the edges of the cross-module call graph);
  * **impurities** — the R002 side-effect set (print/time/random/IO and
    global/nonlocal statements) so traced bodies can be followed into
    their callees;
  * **thread entries / traced exprs** — where threads and tracers enter;
  * **donation facts** — names bound to ``jax.jit(..., donate_argnums=…)``
    and which return values alias host numpy memory (R010);
  * **lifecycle facts** — threads/executors spawned, daemonized, joined
    or shut down (R012);
  * **module constants** — top-level string and tuple-of-string
    assignments (``EPOCH_KEY = "_epoch"``, ``COMMANDS = (...)``) so the
    message-flow pass (rpcflow.py, R016/R018) resolves wire-key
    spellings and command registries without re-walking any tree.

Summaries keep the parsed AST nodes (no re-parse, no source copies); the
``Program`` object owns the module table and the import-resolved call
graph (callgraph.py).  Like the whole analyzer this imports none of the
checked code and no jax.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from locust_tpu.analysis.callgraph import CallGraph, module_imports
from locust_tpu.analysis.core import call_name, unparse

_LOCKISH = ("lock", "mutex", "semaphore", "cond")

_TRACER_RE = re.compile(
    r"(^|\.)(jit|shard_map|compat_shard_map|pallas_call)$"
)
_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "socket.", "os.environ")
_SANCTIONED = ("debug.print", "debug_print")


def is_lock_ctx(item: ast.withitem) -> bool:
    src = unparse(item.context_expr).lower()
    return any(word in src for word in _LOCKISH)


def module_name(rel: str) -> str:
    """Repo-relative path -> dotted module name ("bench.py" -> "bench",
    "locust_tpu/obs/__init__.py" -> "locust_tpu.obs")."""
    name = rel[:-3] if rel.endswith(".py") else rel
    if name.endswith("/__init__"):
        name = name[: -len("/__init__")]
    return name.replace("/", ".")


@dataclasses.dataclass
class WriteFact:
    line: int
    col: int
    desc: str      # "self.state" / "total"
    locked: bool   # a `with <lock>:` encloses the write locally


@dataclasses.dataclass
class CallFact:
    line: int
    col: int
    callee: str    # dotted source text of the callee ("self._handle")
    locked: bool
    node: ast.Call


@dataclasses.dataclass
class SpawnFact:
    kind: str          # "thread" | "executor"
    line: int
    col: int
    bound: str | None  # dotted target text when assigned, else None
    daemon: bool       # daemon=True at the constructor
    in_with: bool      # executor used as a `with` context (auto-shutdown)
    chained_start: bool  # Thread(...).start() with no binding


class FunctionSummary:
    """One def/async def (or an entry lambda): its shared-state writes,
    impure statements and outgoing calls, each with local lock context.
    Facts cover the WHOLE subtree including nested defs (the entry
    function's view of its closure, matching the single-pass engine);
    the call graph therefore never follows a call into a callee nested
    inside the caller — those lines were already scanned."""

    def __init__(self, node, module: "ModuleSummary", nested: bool):
        self.node = node
        self.module = module
        self.rel = module.rel
        self.name = getattr(node, "name", "<lambda>")
        self.lineno = node.lineno
        self.nested = nested
        self.writes: list[WriteFact] = []
        self.impurities: list[tuple[int, int, str]] = []
        self.calls: list[CallFact] = []
        self._scan()

    # ------------------------------------------------------------- scanning

    def _scan(self) -> None:
        shared = _declared_shared(self.node)
        body = self.node.body
        for stmt in body if isinstance(body, list) else [body]:
            self._visit(stmt, shared, locked=False)

    def _visit(self, node: ast.AST, shared: set[str], locked: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = locked or any(is_lock_ctx(i) for i in node.items)
            for child in ast.iter_child_nodes(node):
                self._visit(child, shared, inner)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                desc = _shared_target(t, shared)
                if desc:
                    self.writes.append(
                        WriteFact(node.lineno, node.col_offset, desc, locked)
                    )
        elif isinstance(node, ast.Call):
            callee = call_name(node)
            if callee:
                self.calls.append(
                    CallFact(node.lineno, node.col_offset, callee,
                             locked, node)
                )
            if callee == "print":
                self.impurities.append(
                    (node.lineno, node.col_offset, "print() call"))
            elif callee == "open":
                self.impurities.append(
                    (node.lineno, node.col_offset, "file I/O (open)"))
            elif any(callee.startswith(p) for p in _IMPURE_PREFIXES):
                if not callee.endswith(_SANCTIONED):
                    self.impurities.append(
                        (node.lineno, node.col_offset,
                         f"host side effect ({callee})"))
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            self.impurities.append(
                (node.lineno, node.col_offset,
                 f"{kind} write ({', '.join(node.names)})"))
        for child in ast.iter_child_nodes(node):
            self._visit(child, shared, locked)


def _shared_target(t: ast.AST, shared: set[str]) -> str | None:
    root = t
    while isinstance(root, ast.Subscript):
        root = root.value
    if isinstance(root, ast.Attribute):
        base = root.value
        if isinstance(base, ast.Name) and base.id == "self":
            return f"self.{root.attr}"
    if isinstance(root, ast.Name) and root.id in shared:
        return root.id
    return None


def _declared_shared(fn: ast.AST) -> set[str]:
    """Names ``fn`` shares beyond its own frame: ``global`` anywhere in
    its subtree, ``nonlocal`` only when declared BY ``fn`` itself (a
    nested def's nonlocal refers to this function's own locals, which
    are private to its thread).  One traversal, tracking nesting depth
    (this runs per function; two subtree walks here dominated the
    summaries build)."""
    names: set[str] = set()
    stack: list[tuple[ast.AST, bool]] = [(fn, False)]
    first = True
    while stack:
        node, nested = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and not first:
            nested = True
        first = False
        if isinstance(node, ast.Global):
            names.update(node.names)
        elif isinstance(node, ast.Nonlocal) and not nested:
            names.update(node.names)
        stack.extend((c, nested) for c in ast.iter_child_nodes(node))
    return names


# --------------------------------------------------------- module summaries


def _thread_entries(nodes: list):
    """(expr, how) for every function reference handed to a thread.
    ``nodes`` is the module's shared pre-walked node list — these
    module-level scans used to each re-walk the tree, and the repeated
    traversal (not the matching) was the summaries-build hot spot."""
    executors = _executor_names(nodes)
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        callee = call_name(node)
        if callee.split(".")[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    yield kw.value, "threading.Thread target"
        elif isinstance(node.func, ast.Attribute):
            owner = node.func.value
            owner_name = owner.id if isinstance(owner, ast.Name) else None
            if node.func.attr == "submit" and node.args:
                yield node.args[0], "executor.submit callable"
            elif (
                node.func.attr == "map"
                and node.args
                and owner_name in executors
            ):
                yield node.args[0], "executor.map callable"


def _executor_names(nodes: list) -> set[str]:
    names: set[str] = set()
    for node in nodes:
        if isinstance(node, ast.withitem):
            ctx, opt = node.context_expr, node.optional_vars
            if (
                isinstance(ctx, ast.Call)
                and "Executor" in call_name(ctx)
                and isinstance(opt, ast.Name)
            ):
                names.add(opt.id)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if "Executor" in call_name(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _traced_fn_exprs(nodes: list):
    """Expressions positioned as the to-be-traced function: first arg of
    tracer calls (unwrapping nested tracer calls), plus decorated defs
    (the whole decorator is matched, for the dominant
    ``@functools.partial(jax.jit, ...)`` idiom)."""
    for node in nodes:
        if isinstance(node, ast.Call) and _TRACER_RE.search(call_name(node)):
            if node.args:
                arg = node.args[0]
                while (
                    isinstance(arg, ast.Call)
                    and _TRACER_RE.search(call_name(arg))
                    and arg.args
                ):
                    arg = arg.args[0]
                yield arg
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                src = unparse(dec)
                if _TRACER_RE.search(src) or re.search(
                    r"\b(jit|shard_map|pallas_call)\b", src
                ):
                    yield node
                    break


def _donate_positions(expr: ast.AST) -> tuple[int, ...]:
    """Int argument positions a ``donate_argnums=`` expression can take:
    every int constant anywhere in it (covers literal tuples and the
    ``(0,) if cfg.donate_fold else ()`` conditional idiom)."""
    pos = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Constant) and type(n.value) is int:
            pos.add(n.value)
    return tuple(sorted(pos))


def _donating(nodes: list) -> dict[str, tuple[int, ...]]:
    """name/attr -> donated arg positions, for every binding of a
    ``jax.jit(fn, donate_argnums=...)`` result and every def decorated
    with a donating jit.  A kwarg spelled as a local Name is resolved
    through the module's simple ``name = expr`` assignments."""
    assigns: dict[str, list[ast.AST]] = {}
    for node in nodes:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    assigns.setdefault(t.id, []).append(node.value)

    def positions_of(call: ast.Call) -> tuple[int, ...]:
        name = call_name(call)
        is_tracer = bool(_TRACER_RE.search(name))
        if not is_tracer and name.split(".")[-1] == "partial":
            # functools.partial(jax.jit, donate_argnums=...) decorators.
            is_tracer = any(
                _TRACER_RE.search(unparse(a)) for a in call.args
            )
        if not is_tracer:
            return ()
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            val = kw.value
            if isinstance(val, ast.Name):
                pos: set[int] = set()
                for expr in assigns.get(val.id, []):
                    pos.update(_donate_positions(expr))
                return tuple(sorted(pos))
            return _donate_positions(val)
        return ()

    out: dict[str, tuple[int, ...]] = {}
    for node in nodes:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = positions_of(node.value)
            if pos:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = pos
                    elif isinstance(t, ast.Attribute):
                        out[t.attr] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    pos = positions_of(dec)
                    if pos:
                        out[node.name] = pos
    return out


def _spawns(nodes: list):
    """Thread/executor lifecycle facts for R012."""
    bound: dict[int, str] = {}  # id(call node) -> dotted target text
    with_ctx: set[int] = set()
    joined: set[str] = set()
    shutdown: set[str] = set()
    daemon_after: set[str] = set()  # `t.daemon = True` after construction
    for node in nodes:
        if isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call):
                for t in node.targets:
                    if isinstance(t, (ast.Name, ast.Attribute)):
                        bound[id(node.value)] = unparse(t)
            if (
                isinstance(node.value, ast.Constant)
                and node.value.value is True
            ):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon":
                        daemon_after.add(unparse(t.value))
        elif isinstance(node, ast.withitem):
            with_ctx.add(id(node.context_expr))
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr == "join":
                joined.add(unparse(node.func.value))
            elif node.func.attr == "shutdown":
                shutdown.add(unparse(node.func.value))

    spawns: list[SpawnFact] = []
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        callee = call_name(node)
        leaf = callee.split(".")[-1]
        if leaf == "Thread":
            daemon = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            name = bound.get(id(node))
            spawns.append(SpawnFact(
                "thread", node.lineno, node.col_offset, name,
                daemon or (name in daemon_after if name else False),
                in_with=False, chained_start=False,
            ))
        elif "Executor" in leaf:
            spawns.append(SpawnFact(
                "executor", node.lineno, node.col_offset,
                bound.get(id(node)), daemon=False,
                in_with=id(node) in with_ctx, chained_start=False,
            ))
    # Thread(...).start() with no binding: the call node is the .start
    # attribute's receiver.
    for node in nodes:
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "start"
            and isinstance(node.func.value, ast.Call)
        ):
            inner = node.func.value
            for s in spawns:
                if (s.line, s.col) == (inner.lineno, inner.col_offset):
                    s.chained_start = True
    return spawns, joined, shutdown


def _const_str_seq(v: ast.AST) -> tuple | None:
    """A tuple/list/set of string constants (command registries are
    spelled this way), following ``+`` concatenation of resolvable
    halves."""
    if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for e in v.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(v, ast.BinOp) and isinstance(v.op, ast.Add):
        left = _const_str_seq(v.left)
        right = _const_str_seq(v.right)
        if left is not None and right is not None:
            return left + right
    return None


def _module_consts(tree: ast.Module):
    """Top-level ``NAME = "str"`` and ``NAME = ("a", "b", ...)`` tables —
    the wire-key constants (protocol.EPOCH_KEY) and command registries
    the rpcflow pass resolves spellings through (R016/R018)."""
    strs: dict[str, str] = {}
    seqs: dict[str, tuple] = {}
    for stmt in tree.body:
        if not (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
        ):
            continue
        name, v = stmt.targets[0].id, stmt.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            strs[name] = v.value
        else:
            items = _const_str_seq(v)
            if items is not None:
                seqs[name] = items
    return strs, seqs


class ModuleSummary:
    def __init__(self, sf):
        self.sf = sf
        self.rel = sf.rel
        self.name = module_name(sf.rel)
        tree = sf.tree
        self.imports = module_imports(
            tree, self.name, is_package=sf.rel.endswith("/__init__.py")
        )
        self.functions: list[FunctionSummary] = []
        self.by_name: dict[str, list[FunctionSummary]] = {}
        self.top_by_name: dict[str, list[FunctionSummary]] = {}
        self._collect(tree, nested=False)
        # One walk, shared by every module-level scan below: re-walking
        # the tree per scan (not the matching) was the build hot spot.
        nodes = list(ast.walk(tree))
        self.thread_entries = list(_thread_entries(nodes))
        self.traced_exprs = list(_traced_fn_exprs(nodes))
        self.donating = _donating(nodes)
        self.spawns, self.joined, self.shutdown = _spawns(nodes)
        self.str_consts, self.seq_consts = _module_consts(tree)

    def _collect(self, node: ast.AST, nested: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fsum = FunctionSummary(child, self, nested)
                self.functions.append(fsum)
                self.by_name.setdefault(child.name, []).append(fsum)
                if not nested:
                    self.top_by_name.setdefault(child.name, []).append(fsum)
                self._collect(child, nested=True)
            else:
                self._collect(child, nested)

    def lambda_summary(self, node: ast.Lambda) -> FunctionSummary:
        """Ad-hoc summary for an entry lambda (writes are impossible in a
        lambda body; calls and impurities are what following needs)."""
        return FunctionSummary(node, self, nested=True)


class Program:
    """The phase-1 product: every parsed file's module summary plus the
    import-resolved call graph the phase-2 rules traverse."""

    def __init__(self, files, root: str):
        self.root = root
        self.files = files
        self.by_rel = {f.rel: f for f in files}
        self.modules: dict[str, ModuleSummary] = {}
        self.by_module_rel: dict[str, ModuleSummary] = {}
        for sf in files:
            if sf.tree is None:
                continue
            mod = ModuleSummary(sf)
            self.modules[mod.name] = mod
            self.by_module_rel[mod.rel] = mod
        self.graph = CallGraph(self)


def build_program(files, root: str) -> Program:
    return Program(files, root)
