"""R016/R017/R018 — RPC schema conformance, silent thread death, chaos
coverage (the cross-process message-flow rules; facts from rpcflow.py).

R016 (rpc schema drift, two-sided): the registries police cmd NAMES;
this rule polices the key schemas on both sides of every cmd.  A send
site whose cmd is unregistered or has no resolvable dispatcher arm is a
*phantom cmd* — never baselineable debt (``Finding.baselineable`` is
False; ``--write-baseline`` refuses it).  A handler read with no default
(``req["k"]``) must be supplied by a send site; a key a closed send site
carries that no arm ever reads is dead weight on the wire; a reply key a
client reads that no arm produces is the stale-epoch-reply-shape
incident (PR 14/15) as a machine check.  Epoch-fenced cmds (the ship
plane + the fenced pool RPCs) must carry ``protocol.EPOCH_KEY`` at every
send site.  All checks gate on CLOSED facts only — an OPEN payload, arm
or reply disables exactly the checks that would need it.

R017 (silent thread death): the shipper, dispatcher and heartbeat loops
are "never a hang" tiers where a silently dead thread IS the hang.  Two
shapes: a ``threading.Thread`` target whose body can exit via an
uncaught exception (no broad except in it or one resolvable call away;
executor callables are covered when the spawning module reads futures —
``.result()`` re-raises there), and an ``except Exception: pass``-shaped
swallow anywhere in ``locust_tpu/`` (a broad handler whose body neither
calls anything — no logging, no recording — nor re-raises nor uses the
bound exception).

R018 (chaos-coverage drift): every discovered cmd needs a plane
(job/data/control); every job- or data-plane cmd must reach a
``faultplan`` hook (fire/mangle/delay/damage_file) within two call hops
of its handler arm, dispatcher, or send path — excluding the generic
frame-layer hooks (rpc.connect / rpc.frame in distributor/protocol.py),
which fire for every frame and therefore distinguish nothing.  New RPCs
cannot ship chaos-blind (docs/FAULTS.md).
"""

from __future__ import annotations

import ast

from locust_tpu.analysis import rpcflow
from locust_tpu.analysis.core import Finding, Rule, call_name, unparse

# The wire tiers: every send_frame/recv_frame caller lives here.  The
# analysis package itself is deliberately OUT of scope — rpcflow
# analyzing its own helper-matching code manufactures phantom helpers.
DEFAULT_SCOPE = ("locust_tpu/serve/", "locust_tpu/distributor/")
DEFAULT_REGISTRIES = (
    ("locust_tpu/serve/daemon.py", "SERVE_COMMANDS"),
    ("locust_tpu/distributor/protocol.py", "COMMANDS"),
    ("locust_tpu/distributor/protocol.py", "SHIP_COMMANDS"),
)
DEFAULT_SEEDS = (("send_frame", 1),)


class _RpcRuleBase(Rule):
    """Shared rpcflow access: R016 and R018 with identical (scope,
    registries, seeds) share ONE RpcProgram build per run (cached on the
    Program; pinned by tests)."""

    # Overridable for fixture trees in tests (R004/R013 pattern).
    scope = DEFAULT_SCOPE
    registries = DEFAULT_REGISTRIES
    seeds = DEFAULT_SEEDS

    def _rpc(self, program) -> rpcflow.RpcProgram:
        return rpcflow.get(program, self.scope, self.registries, self.seeds)


class RpcSchemaRule(_RpcRuleBase):
    rule_id = "R016"
    title = "rpc schema drift between send sites and handler arms"

    # Epoch fencing: every cmd in these registries plus these named cmds
    # must carry protocol.EPOCH_KEY at every closed send site.
    fenced_registry_vars = ("SHIP_COMMANDS",)
    fenced_cmds = ("serve_batch", "plan_stage")
    epoch_key = "_epoch"

    def check_program(self, program):
        rp = self._rpc(program)
        if not rp.registry_cmds:
            return  # no registries in this tree (fixture subset)
        fenced = set(self.fenced_cmds)
        for (_, var), cmds in rp.registry_cmds.items():
            if var in self.fenced_registry_vars:
                fenced.update(cmds)
        registry_names = ", ".join(
            sorted({var for _, var in rp.registry_cmds})
        )
        phantom_seen: set = set()
        for s in rp.sites:
            if s.cmd not in rp.all_cmds:
                f = Finding(
                    self.rule_id, s.rel, s.line, s.col,
                    f"send site for cmd {s.cmd!r} — not in any command "
                    f"registry ({registry_names}); register it or fix the "
                    "typo (a phantom cmd has no handler and is never "
                    "baselineable debt)",
                )
                f.baselineable = False
                yield f
                continue
            if not rp.arm_index.get(s.cmd) and s.cmd not in phantom_seen:
                phantom_seen.add(s.cmd)
                f = Finding(
                    self.rule_id, s.rel, s.line, s.col,
                    f"cmd {s.cmd!r} is sent and registered but no "
                    "dispatcher arm handles it (phantom cmd — never "
                    "baselineable debt); add the arm or retire the sender",
                )
                f.baselineable = False
                yield f
            if (
                s.cmd in fenced
                and self.epoch_key not in s.payload.all_keys()
                and not s.payload.open
            ):
                yield Finding(
                    self.rule_id, s.rel, s.line, s.col,
                    f"epoch-fenced cmd {s.cmd!r} sent without "
                    f"protocol.EPOCH_KEY ({self.epoch_key!r}) — an "
                    "unfenced ship/stage RPC lets a partitioned old "
                    "primary be honored after promotion (docs/SERVING.md "
                    "fencing)",
                )
            arms = rp.arm_index.get(s.cmd, [])
            if s.reply_reads and arms and all(
                not a.open_reply for a in arms
            ):
                allowed = rpcflow.GENERIC_REPLY_KEYS.union(
                    *[a.reply_keys for a in arms]
                )
                for k in sorted(s.reply_reads - allowed):
                    yield Finding(
                        self.rule_id, s.rel, s.line, s.col,
                        f"client reads reply key {k!r} for cmd {s.cmd!r} "
                        "but no handler arm produces it (the stale-epoch-"
                        "reply-shape incident class)",
                    )
        for cmd in sorted(rp.sites_by_cmd):
            sites = rp.sites_by_cmd[cmd]
            arms = rp.arm_index.get(cmd, [])
            closed = [
                s for s in sites if not s.payload.open and not s.synthetic
            ]
            any_open = any(s.payload.open or s.synthetic for s in sites)
            if closed and not any_open:
                supplied: set = set()
                for s in closed:
                    supplied |= s.payload.all_keys()
                for a in arms:
                    missing = a.required - rpcflow.WIRE_META_KEYS - supplied
                    for k in sorted(missing):
                        yield Finding(
                            self.rule_id, a.rel, a.line, 0,
                            f"handler arm for cmd {cmd!r} requires key "
                            f"{k!r} (req[...] with no default) but no send "
                            "site supplies it — every request for this cmd "
                            "raises KeyError in the handler",
                        )
            if arms and all(not a.open_reads for a in arms):
                consumed = set(rpcflow.WIRE_META_KEYS)
                for a in arms:
                    consumed |= a.required | a.optional
                for s in closed:
                    for k in sorted(s.payload.all_keys() - consumed):
                        yield Finding(
                            self.rule_id, s.rel, s.line, s.col,
                            f"dead payload key {k!r} sent with cmd {cmd!r} "
                            "— no handler arm reads it; drop it or wire up "
                            "the read (schema drift, the PR 7 "
                            "unknown_job class)",
                        )


def _broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _has_broad_try(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Try) and any(
            _broad_handler(h) for h in n.handlers
        ):
            return True
    return False


class SilentThreadDeathRule(Rule):
    rule_id = "R017"
    title = "silent thread death / silent broad-except swallow"

    scope = ("locust_tpu/",)

    # --------------------------------------------------- swallow shapes

    def check_file(self, f, root):
        if not f.rel.startswith(tuple(self.scope)) or f.tree is None:
            return
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _broad_handler(node):
                continue
            has_call = has_raise = uses_exc = False
            for stmt in node.body:
                for x in ast.walk(stmt):
                    if isinstance(x, ast.Call):
                        has_call = True
                    elif isinstance(x, ast.Raise):
                        has_raise = True
                    elif (
                        isinstance(x, ast.Name)
                        and node.name is not None
                        and x.id == node.name
                    ):
                        uses_exc = True
            if has_call or has_raise or uses_exc:
                continue
            yield Finding(
                self.rule_id, f.rel, node.lineno, node.col_offset,
                "broad except swallows the exception without logging, "
                "recording, or re-raising — in the never-a-hang tiers a "
                "silently eaten error is invisible until it IS the hang; "
                "log it (logger.warning/debug) or noqa with the reason "
                "the silence is safe",
            )

    # ------------------------------------------------- thread-death arm

    def check_program(self, program):
        seen: set = set()
        for mod in program.modules.values():
            if not mod.rel.startswith(tuple(self.scope)):
                continue
            module_reads_futures = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "result"
                for n in ast.walk(mod.sf.tree)
            )
            for ref, how in mod.thread_entries:
                if (
                    how.startswith("executor")
                    and module_reads_futures
                ):
                    continue  # futures re-raise at .result()
                for fn in self._resolve_entry(program, mod, ref):
                    key = (fn.rel, fn.lineno)
                    if key in seen:
                        continue
                    seen.add(key)
                    if not fn.calls:
                        continue  # nothing in the body can raise much
                    if self._protected(program, fn):
                        continue
                    yield Finding(
                        self.rule_id, fn.rel, fn.lineno,
                        fn.node.col_offset,
                        f"thread entry '{fn.name}' ({how}) can exit via "
                        "an uncaught exception — the thread dies silently "
                        "and in the never-a-hang tiers a dead "
                        "shipper/dispatcher/heartbeat loop IS the hang; "
                        "wrap the body in a broad except that logs (and "
                        "keeps the loop alive or marks the owner dead)",
                    )

    @staticmethod
    def _resolve_entry(program, mod, ref):
        if isinstance(ref, ast.Lambda):
            return [mod.lambda_summary(ref)]
        if isinstance(ref, ast.Name):
            return program.graph.resolve(mod, ref.id, include_nested=True)
        if isinstance(ref, ast.Attribute):
            return program.graph.resolve(
                mod, unparse(ref), include_nested=True
            )
        return []

    @staticmethod
    def _protected(program, fn) -> bool:
        if _has_broad_try(fn.node):
            return True
        for c in fn.calls:
            for t in program.graph.resolve(fn.module, c.callee,
                                           include_nested=True):
                if t.node is fn.node:
                    continue
                if _has_broad_try(t.node):
                    return True
        return False


class ChaosCoverageRule(_RpcRuleBase):
    rule_id = "R018"
    title = "rpc cmd without reachable faultplan chaos coverage"

    # Every discovered cmd must be classified; job/data-plane cmds must
    # reach a faultplan hook.  A NEW cmd therefore fails loudly here
    # until it is classified AND chaos-covered (or exempted with a
    # documented reason in ``exempt``).
    planes = {
        "submit": "job", "map": "job", "serve_batch": "job",
        "plan_stage": "job",
        "fetch": "data", "ship": "data", "ship_catchup": "data",
        "ship_spill": "data",
        "ping": "control", "status": "control", "result": "control",
        "cancel": "control", "invalidate": "control", "stats": "control",
        "serve_stats": "control", "shutdown": "control",
        "promote": "control",
    }
    exempt: dict = {}  # cmd -> documented reason
    # Hooks in the frame layer fire for EVERY frame — they distinguish
    # nothing per-cmd and do not count as coverage.
    exclude_hook_rels = ("locust_tpu/distributor/protocol.py",)
    generic_sites = ("rpc.connect", "rpc.frame")
    hook_names = ("fire", "mangle", "delay", "damage_file")
    hops = 2

    def check_program(self, program):
        rp = self._rpc(program)
        if not rp.registry_cmds:
            return
        discovered = set(rp.all_cmds) | set(rp.sites_by_cmd)
        for cmd in sorted(discovered):
            if cmd in self.exempt:
                continue
            rel, line = self._loc(rp, cmd)
            plane = self.planes.get(cmd)
            if plane is None:
                yield Finding(
                    self.rule_id, rel, line, 0,
                    f"cmd {cmd!r} has no plane classification — add it to "
                    "R018.planes as job/data/control (job and data cmds "
                    "then need a reachable faultplan site) or exempt it "
                    "with a documented reason",
                )
                continue
            if plane == "control":
                continue
            if not self._covered(
                program, self._seeds(rp, cmd)
            ) and not self._dispatcher_hook(rp, cmd):
                yield Finding(
                    self.rule_id, rel, line, 0,
                    f"{plane}-plane cmd {cmd!r} is not reachable from any "
                    "faultplan chaos site (fire/mangle/delay/damage_file "
                    "outside the generic frame layer) — new RPCs must not "
                    "ship chaos-blind; add a site (docs/FAULTS.md) or "
                    "exempt it with a documented reason",
                )

    @staticmethod
    def _loc(rp, cmd):
        for a in rp.arm_index.get(cmd, []):
            return a.rel, a.line
        for s in rp.sites_by_cmd.get(cmd, []):
            return s.rel, s.line
        return next(iter(rp.registry_cmds))[0], 1

    @staticmethod
    def _seeds(rp, cmd):
        # The DISPATCHER fn is excluded: from it, every handler arm is
        # one hop away, so one hook anywhere (serve.admit in
        # _cmd_submit) would vacuously "cover" every dispatched cmd.
        # Coverage must come from THIS cmd's arm delegates or send path;
        # a dispatcher-body hook counts only via _dispatcher_hook (and
        # only when it is cmd-parameterized).
        arms = rp.arm_index.get(cmd, [])
        disp_ids = {id(a.dispatcher.node) for a in arms if a.dispatcher}
        fns = []
        for a in arms:
            fns.extend(a.fns)
        for s in rp.sites_by_cmd.get(cmd, []):
            fns.extend(s.fns)
        out, ids = [], set()
        for fn in fns:
            if id(fn.node) not in ids and id(fn.node) not in disp_ids:
                ids.add(id(fn.node))
                out.append(fn)
        return out

    def _dispatcher_hook(self, rp, cmd) -> bool:
        """A hook in the dispatch loop itself covers every cmd it
        dispatches — but only when parameterized by the cmd (the
        worker's ``faultplan.delay("rpc.delay", cmd=cmd, ...)``): an
        unparameterized dispatcher hook cannot target one cmd, so it
        distinguishes nothing."""
        for a in rp.arm_index.get(cmd, []):
            if a.dispatcher is None or a.dispatcher.rel in \
                    self.exclude_hook_rels:
                continue
            for n in ast.walk(a.dispatcher.node):
                if (
                    isinstance(n, ast.Call)
                    and self._hook_call(n)
                    and any(kw.arg == "cmd" for kw in n.keywords)
                ):
                    return True
        return False

    def _covered(self, program, seeds) -> bool:
        frontier = list(seeds)
        ids = {id(fn.node) for fn in frontier}
        for _ in range(self.hops + 1):
            nxt = []
            for fn in frontier:
                if self._has_hook(fn):
                    return True
                for c in fn.calls:
                    for t in program.graph.resolve(
                        fn.module, c.callee, include_nested=True
                    ):
                        if id(t.node) not in ids:
                            ids.add(id(t.node))
                            nxt.append(t)
            frontier = nxt
            if not frontier:
                break
        return False

    def _has_hook(self, fn) -> bool:
        if fn.rel in self.exclude_hook_rels:
            return False
        return any(
            isinstance(n, ast.Call) and self._hook_call(n)
            for n in ast.walk(fn.node)
        )

    def _hook_call(self, n: ast.Call) -> bool:
        name = call_name(n)
        parts = name.split(".")
        if parts[-1] not in self.hook_names:
            return False
        if len(parts) < 2 or parts[-2] != "faultplan":
            return False
        return (
            bool(n.args)
            and isinstance(n.args[0], ast.Constant)
            and isinstance(n.args[0].value, str)
            and n.args[0].value not in self.generic_sites
        )
