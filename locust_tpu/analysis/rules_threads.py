"""R001 — thread-shared state written without a lock (lockset heuristic).

The incident: the distributor's attempt/fetch/heartbeat threads (PR 1/2)
were hardened against "abandoned-loser pool-shutdown races" by code
review, not by tooling.  This rule is the Eraser-style (Savage et al.,
1997) static shadow of that review: a function that RUNS ON A THREAD
(``threading.Thread(target=...)``, ``executor.submit(fn)``,
``executor.map(fn)``) must not write ``self.*`` attributes, ``global``
names, or ``nonlocal`` closure slots outside a ``with <lock>:`` block.

Heuristics (documented in docs/ANALYSIS.md):

  * entry points are resolved BY NAME within the module (callees of the
    thread entry are not followed — no interprocedural call graph);
  * "a lock" is any ``with`` context whose expression mentions
    lock/mutex/semaphore/cond (``with self._lock:`` etc.);
  * local variables and attribute writes on non-``self`` locals are NOT
    flagged (per-shard locals like ``stats.winner`` are thread-private
    by construction in this codebase; flagging them would bury the
    signal).
"""

from __future__ import annotations

import ast

from locust_tpu.analysis.core import Finding, Rule, call_name, unparse

_LOCKISH = ("lock", "mutex", "semaphore", "cond")


def _is_lock_ctx(item: ast.withitem) -> bool:
    src = unparse(item.context_expr).lower()
    return any(word in src for word in _LOCKISH)


def _executor_names(fn: ast.AST) -> set[str]:
    """Names bound to ThreadPoolExecutor-ish constructors in this scope
    (``with ThreadPoolExecutor(...) as ex`` / ``pool = ...Executor(...)``)."""
    names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.withitem):
            ctx, opt = node.context_expr, node.optional_vars
            if (
                isinstance(ctx, ast.Call)
                and "Executor" in call_name(ctx)
                and isinstance(opt, ast.Name)
            ):
                names.add(opt.id)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if "Executor" in call_name(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _entry_refs(tree: ast.Module):
    """(expr, how) for every function reference handed to a thread."""
    executors = _executor_names(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = call_name(node)
        if callee.split(".")[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    yield kw.value, "threading.Thread target"
        elif isinstance(node.func, ast.Attribute):
            owner = node.func.value
            owner_name = owner.id if isinstance(owner, ast.Name) else None
            if node.func.attr == "submit" and node.args:
                yield node.args[0], "executor.submit callable"
            elif (
                node.func.attr == "map"
                and node.args
                and owner_name in executors
            ):
                yield node.args[0], "executor.map callable"


def _resolve(ref: ast.AST, by_name: dict):
    """Thread-entry reference -> function nodes (best-effort, by name)."""
    if isinstance(ref, ast.Lambda):
        return [ref]
    if isinstance(ref, ast.Name):
        return by_name.get(ref.id, [])
    if isinstance(ref, ast.Attribute):  # self.method / obj.method
        return by_name.get(ref.attr, [])
    return []


class _WriteScanner:
    """Walk a thread-entry body tracking lock context; collect unlocked
    writes to self.*/global/nonlocal state."""

    def __init__(self, shared_names: set[str]):
        self.shared = shared_names  # global/nonlocal-declared in this fn
        self.hits: list[tuple[ast.AST, str]] = []

    def scan(self, node: ast.AST, locked: bool) -> None:
        if isinstance(node, ast.With):
            inner = locked or any(_is_lock_ctx(i) for i in node.items)
            for child in ast.iter_child_nodes(node):
                self.scan(child, inner)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            if not locked:
                for t in targets:
                    desc = self._shared_target(t)
                    if desc:
                        self.hits.append((node, desc))
        for child in ast.iter_child_nodes(node):
            self.scan(child, locked)

    def _shared_target(self, t: ast.AST) -> str | None:
        root = t
        while isinstance(root, ast.Subscript):
            root = root.value
        if isinstance(root, ast.Attribute):
            base = root.value
            if isinstance(base, ast.Name) and base.id == "self":
                return f"self.{root.attr}"
        if isinstance(root, ast.Name) and root.id in self.shared:
            return root.id
        return None


def _declared_shared(fn: ast.AST) -> set[str]:
    """Names this entry function shares across threads: ``global``
    anywhere in its subtree, but ``nonlocal`` only when DECLARED BY the
    entry function itself — a nested def's nonlocal refers to the entry
    function's own locals, which are private to its thread."""
    names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            names.update(node.names)

    def own_statements(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from own_statements(child)

    for node in own_statements(fn):
        if isinstance(node, ast.Nonlocal):
            names.update(node.names)
    return names


class ThreadSharedStateRule(Rule):
    rule_id = "R001"
    title = "thread-shared state written without a lock"

    def check_file(self, f, root):
        tree = f.tree
        by_name: dict[str, list] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        seen: set[int] = set()
        for ref, how in _entry_refs(tree):
            for fn in _resolve(ref, by_name):
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                shared = _declared_shared(fn)
                scanner = _WriteScanner(shared)
                body = fn.body if hasattr(fn, "body") else [fn]
                for stmt in body if isinstance(body, list) else [body]:
                    scanner.scan(stmt, locked=False)
                name = getattr(fn, "name", "<lambda>")
                for node, desc in scanner.hits:
                    yield Finding(
                        self.rule_id,
                        f.rel,
                        node.lineno,
                        node.col_offset,
                        f"'{name}' runs on a thread ({how}) and writes "
                        f"shared state {desc} with no enclosing "
                        "'with <lock>:' — a data race heuristic; guard it "
                        "or noqa with the synchronization argument",
                    )
