"""R001/R012 — thread-shared state and thread/executor lifecycle.

R001 (interprocedural lockset): a function that RUNS ON A THREAD
(``threading.Thread(target=...)``, ``executor.submit(fn)``,
``executor.map(fn)``) must not write ``self.*`` attributes, ``global``
names, or own-``nonlocal`` closure slots outside a ``with <lock>:``
block.  Since the two-phase engine, the Eraser-style (Savage et al.,
1997) lockset follows CALLS from the entry point across modules through
the summaries call graph, with the lock context propagated along the
chain: ``Thread(target=self._loop)`` where ``_loop`` calls ``_once``
which writes ``self._mark`` unlocked is a finding in ``_once`` — the
exact shape the serve dispatcher shipped with (PR 7 review rounds).

Heuristics (documented in docs/ANALYSIS.md):

  * entry points resolve by name (nested defs included); calls resolve
    through the attribution-only call graph (callgraph.py) and only
    into top-level functions/methods — a callee nested in the caller is
    already covered by the caller's whole-subtree summary;
  * "a lock" is any ``with`` whose context expression mentions
    lock/mutex/semaphore/cond; a call made INSIDE such a ``with`` marks
    its whole callee chain as lock-covered ("caller holds the lock"
    conventions like daemon._corpus_put stay silent);
  * locals and attribute writes on non-``self`` receivers are not
    flagged (thread-private by construction in this codebase).

R012 (thread/executor lifecycle): every ``threading.Thread`` in
``locust_tpu/`` must be daemonized or joined somewhere in its module;
every bound executor must be ``with``-managed or ``.shutdown(...)``.  A
non-daemon thread nobody joins outlives crashes and wedges interpreter
exit — the dispatcher-join and warm-writer-close review incidents
(serve/daemon.py close(), io/snapshot.py close()) as a machine check.
"""

from __future__ import annotations

import ast

from locust_tpu.analysis.core import Finding, Rule, unparse


class ThreadSharedStateRule(Rule):
    rule_id = "R001"
    title = "thread-shared state written without a lock"

    _MAX_DEPTH = 8

    def check_program(self, program):
        emitted: set[tuple] = set()
        for mod in program.modules.values():
            for ref, how in mod.thread_entries:
                for fn in self._resolve_entry(program, mod, ref):
                    yield from self._visit(
                        program, fn, how, entry=fn.name, chain=(fn.name,),
                        locked=False, depth=0, visited={}, emitted=emitted,
                    )

    def _resolve_entry(self, program, mod, ref):
        if isinstance(ref, ast.Lambda):
            return [mod.lambda_summary(ref)]
        if isinstance(ref, ast.Name):
            return program.graph.resolve(mod, ref.id, include_nested=True)
        if isinstance(ref, ast.Attribute):
            return program.graph.resolve(
                mod, unparse(ref), include_nested=True
            )
        return []

    def _visit(self, program, fn, how, entry, chain, locked, depth,
               visited, emitted):
        # Revisit only when arriving with a WEAKER lock context than any
        # prior visit (unlocked findings dominate).  A depth-truncated
        # visit is NOT recorded: it never explored its callees, and
        # marking it would blind a later shallower path (the emitted-set
        # dedups any re-reported writes; depth still bounds recursion).
        prev = visited.get(id(fn.node))
        if prev is not None and (prev is False or locked):
            return
        if depth < self._MAX_DEPTH:
            visited[id(fn.node)] = locked
        for w in fn.writes:
            if locked or w.locked:
                continue
            key = (fn.rel, w.line, w.desc)
            if key in emitted:
                continue
            emitted.add(key)
            if len(chain) == 1:
                detail = f"'{fn.name}' runs on a thread ({how})"
            else:
                detail = (
                    f"'{fn.name}' is reached from thread entry "
                    f"'{entry}' ({how}) via {' -> '.join(chain)}"
                )
            yield Finding(
                self.rule_id, fn.rel, w.line, w.col,
                f"{detail} and writes shared state {w.desc} with no "
                "enclosing 'with <lock>:' on the call path — a data race "
                "heuristic; guard it or noqa with the synchronization "
                "argument",
            )
        if depth >= self._MAX_DEPTH:
            return
        for c in fn.calls:
            targets = program.graph.resolve(fn.module, c.callee)
            for callee in targets:
                if callee.node is fn.node:
                    continue
                yield from self._visit(
                    program, callee, how, entry,
                    chain + (callee.name,), locked or c.locked,
                    depth + 1, visited, emitted,
                )


class ThreadLifecycleRule(Rule):
    rule_id = "R012"
    title = "thread/executor without a daemon flag, join, or shutdown"

    def check_program(self, program):
        for mod in program.modules.values():
            if not mod.rel.startswith("locust_tpu/"):
                continue  # tests/scripts own their process lifetime
            for s in mod.spawns:
                if s.kind == "thread":
                    if s.daemon:
                        continue
                    if s.bound is not None and s.bound in mod.joined:
                        continue
                    if s.bound is None and not s.chained_start:
                        continue  # passed/returned: can't attribute
                    where = (
                        f"bound to {s.bound!r}" if s.bound
                        else "started inline"
                    )
                    yield Finding(
                        self.rule_id, mod.rel, s.line, s.col,
                        f"non-daemon Thread {where} is never joined in "
                        "this module — it outlives crashes and wedges "
                        "interpreter exit; pass daemon=True or join it on "
                        "a reachable close path (the serve dispatcher-join "
                        "/ warm-writer-close incidents)",
                    )
                else:  # executor
                    if s.in_with:
                        continue
                    if s.bound is not None and s.bound in mod.shutdown:
                        continue
                    if s.bound is None:
                        continue  # unattributable construction
                    yield Finding(
                        self.rule_id, mod.rel, s.line, s.col,
                        f"executor bound to {s.bound!r} has no "
                        "``with``-scope and no .shutdown(...) call in "
                        "this module — worker threads leak past the work "
                        "they were built for; scope it or shut it down on "
                        "a reachable close path",
                    )


# ------------------------------------------------------------------ R013

# Blocking primitives whose no-timeout form can park a thread forever.
# join()/wait()/result() are bounded by a timeout ARGUMENT; accept()/
# recv*() are bounded by the socket's settimeout() deadline instead.
_ARG_BOUNDED = {"join", "wait", "result"}
_SOCKET_BOUNDED = {"accept", "recv", "recv_into", "recvfrom"}
_R013_SCOPES = ("locust_tpu/serve/", "locust_tpu/distributor/")


class UnboundedBlockingRule(Rule):
    """R013 — unbounded-blocking hygiene in the daemon tiers.

    The serve and distributor tiers promise "never a hang": every wait a
    wedged peer, a dead dispatcher, or a saturated pool can extend must
    carry a deadline (the ServeClient.wait / dispatcher-join /
    fetch-pool incidents as a machine check).  Heuristics:

      * ``x.join()`` / ``x.wait()`` / ``x.result()`` with NO positional
        argument and no ``timeout=`` keyword fire (``",".join(parts)``
        and ``os.path.join(a, b)`` always pass arguments, so the
        no-argument form is the thread/future one);
      * ``x.accept()`` / ``x.recv*(...)`` fire unless the receiver is a
        function PARAMETER (the caller owns the socket's deadline — the
        protocol-layer convention) or the enclosing scope visibly calls
        ``settimeout``;
      * deliberate unbounded waits take a reason-noqa, like every rule.
    """

    rule_id = "R013"
    title = "unbounded blocking call in a daemon tier"

    # Overridable for fixture trees in tests (R004/R009/R011 pattern).
    scopes = _R013_SCOPES

    def check_file(self, f, root):
        if not any(f.rel.startswith(p) for p in self.scopes):
            return
        for scope in self._scopes_of(f.tree):
            params = self._params(scope)
            has_settimeout = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "settimeout"
                for n in ast.walk(scope)
            )
            for node in self._own_calls(scope):
                if not isinstance(node.func, ast.Attribute):
                    continue
                leaf = node.func.attr
                if leaf in _ARG_BOUNDED:
                    if node.args or any(
                        kw.arg == "timeout" for kw in node.keywords
                    ):
                        continue
                    yield Finding(
                        self.rule_id, f.rel, node.lineno, node.col_offset,
                        f".{leaf}() without a timeout can park this "
                        "thread forever on a wedged peer/thread — pass "
                        "a timeout (or reason-noqa a deliberate forever-"
                        "wait)",
                    )
                elif leaf in _SOCKET_BOUNDED:
                    recv = node.func.value
                    if isinstance(recv, ast.Name) and recv.id in params:
                        continue  # caller owns the socket deadline
                    if has_settimeout:
                        continue
                    yield Finding(
                        self.rule_id, f.rel, node.lineno, node.col_offset,
                        f".{leaf}() on a socket with no settimeout() in "
                        "this scope blocks forever on a silent peer — "
                        "set a deadline before blocking on the wire",
                    )

    @staticmethod
    def _scopes_of(tree):
        """Module + each function body (innermost wins for ownership)."""
        scopes = [tree]
        scopes.extend(
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        )
        return scopes

    @staticmethod
    def _params(scope):
        if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return set()
        a = scope.args
        return {
            p.arg
            for p in a.args + a.kwonlyargs + a.posonlyargs
        }

    @staticmethod
    def _own_calls(scope):
        """Calls belonging to ``scope`` and not to a nested def (each
        nested def is its own scope in _scopes_of — reporting a call
        from both would duplicate findings)."""
        nested = [
            n for n in ast.walk(scope)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not scope
        ]
        banned = {id(n) for nd in nested for n in ast.walk(nd)}
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and id(n) not in banned:
                yield n
