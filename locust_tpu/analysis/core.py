"""Rule engine: one AST parse per file, per-rule findings, noqa + baseline.

Design constraints (docs/ANALYSIS.md):

  * single pass — each file is read and ``ast.parse``d exactly once; every
    rule sees the same ``SourceFile`` objects;
  * findings are stable — a ``Finding``'s fingerprint hashes the rule id,
    the repo-relative path and the CONTENT of the flagged line (not its
    number), so a baseline survives unrelated edits above the finding;
  * suppression is loud — ``# locust: noqa[R00x] reason`` on the flagged
    line suppresses that rule THERE only, and an empty reason does not
    suppress: it raises R000 instead (a suppression nobody can audit is
    drift waiting to happen);
  * the engine never imports the code it checks (a wedged TPU tunnel in a
    sitecustomize must not be able to hang the gate — CLAUDE.md).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
import subprocess
import time

# R000 is the engine's own rule id: unparseable files and unauditable
# (reason-less) suppressions.  It cannot be suppressed.
ENGINE_RULE = "R000"

# Parse accounting: the one-parse-per-file economy is a pinned contract
# (tests/test_analysis.py) — every ``ast.parse`` of checked source goes
# through ``parse_text`` so the regression test can count them.
_parse_count = 0


def parse_text(text: str) -> ast.Module:
    global _parse_count
    _parse_count += 1
    return ast.parse(text)


def parse_count() -> int:
    return _parse_count


def reset_parse_count() -> None:
    global _parse_count
    _parse_count = 0

_NOQA_RE = re.compile(
    r"#\s*locust:\s*noqa\[([A-Za-z0-9, ]+)\]\s*(.*?)\s*$"
)


@dataclasses.dataclass
class Finding:
    """One rule violation at a file:line."""

    rule_id: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    severity: str = "error"
    baselined: bool = False
    fingerprint: str = ""
    # False marks findings that are never acceptable debt (e.g. R016
    # phantom cmds: a cmd with no handler) — ``--write-baseline`` refuses
    # to record them instead of silently burying a dead RPC.
    baselineable: bool = True

    def format(self) -> str:
        tag = " [baselined]" if self.baselined else ""
        return (
            f"{self.path}:{self.line}:{self.col} {self.rule_id} "
            f"{self.severity}: {self.message}{tag}"
        )

    def as_dict(self) -> dict:
        return {
            "rule": self.rule_id,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "severity": self.severity,
            "baselined": self.baselined,
            "fingerprint": self.fingerprint,
        }


class SourceFile:
    """One parsed source file: text, lines, AST, and its noqa directives."""

    def __init__(self, abspath: str, rel: str, text: str):
        self.abspath = abspath
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = parse_text(text)
        except SyntaxError as e:
            self.parse_error = e
        # line number -> (set of rule ids, reason)
        self.noqa: dict[int, tuple[set[str], str]] = {}
        for i, ln in enumerate(self.lines, start=1):
            m = _NOQA_RE.search(ln)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                self.noqa[i] = (ids, m.group(2).strip())

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class Rule:
    """Base rule.  Subclasses set ``rule_id``/``title`` and override one
    (or more) of the check hooks.  ``check_file`` runs once per analyzed
    python file; ``check_project`` runs once with the full file set (for
    cross-file registry rules) and may emit findings on non-analyzed
    paths (e.g. docs/FAULTS.md); ``check_program`` runs once with the
    phase-1 whole-program summaries (summaries.Program) for the
    interprocedural rules."""

    rule_id = "R999"
    title = "unnamed rule"

    def check_file(self, f: SourceFile, root: str):
        return ()

    def check_project(self, files: list[SourceFile], root: str):
        return ()

    def check_program(self, program):
        return ()


def find_source(files: list[SourceFile], rel: str) -> SourceFile | None:
    """Already-parsed SourceFile for a repo-relative path — registry
    rules use this instead of re-reading/re-parsing their anchor modules
    (the one-parse-per-file economy)."""
    for f in files:
        if f.rel == rel:
            return f
    return None


def parse_registry_module(
    files: list[SourceFile], root: str, rel: str
) -> ast.Module | None:
    """Tree for ``rel``: the phase-1 parse when the file is in the
    analyzed set (the normal case), a counted one-off parse otherwise
    (fixture trees that point a rule at an unanalyzed path)."""
    sf = find_source(files, rel)
    if sf is not None:
        return sf.tree
    try:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            return parse_text(f.read())
    except (OSError, SyntaxError):
        return None


@dataclasses.dataclass
class AnalysisResult:
    findings: list[Finding]  # new + baselined (suppressed excluded)
    new: list[Finding]
    suppressed: int
    n_files: int
    rules: list[str]
    # Per-rule wall time (ms, 1 decimal) so a perf regression in the
    # <10s self-perf pin is attributable to a rule, not just "the run".
    rule_ms: dict[str, float] = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "files": self.n_files,
            "rules": self.rules,
            "rule_ms": self.rule_ms,
            "suppressed": self.suppressed,
            "total": len(self.findings),
            "new": len(self.new),
            "findings": [f.as_dict() for f in self.findings],
        }


def _iter_py_files(paths: list[str], root: str):
    """Expand files/dirs to .py files, skipping caches and VCS dirs."""
    skip_dirs = {"__pycache__", ".git", ".pytest_cache", ".hypothesis", "build"}
    seen = set()
    for p in paths:
        absp = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(absp):
            if absp not in seen:
                seen.add(absp)
                yield absp
        elif os.path.isdir(absp):
            for dirpath, dirnames, filenames in os.walk(absp):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in skip_dirs
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        fp = os.path.join(dirpath, fn)
                        if fp not in seen:
                            seen.add(fp)
                            yield fp


def load_files(paths: list[str], root: str) -> list[SourceFile]:
    files = []
    for absp in _iter_py_files(paths, root):
        try:
            with open(absp, encoding="utf-8", errors="replace") as fh:
                text = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(absp, root)
        files.append(SourceFile(absp, rel, text))
    return files


def _fingerprint(f: Finding, line_text: str, occurrence: int) -> str:
    h = hashlib.sha256(
        f"{f.rule_id}|{f.path}|{line_text}|{occurrence}".encode()
    ).hexdigest()
    return h[:16]


def _assign_fingerprints(findings: list[Finding], by_rel: dict) -> None:
    """Content-addressed fingerprints, disambiguated by occurrence index
    so two identical findings on identical lines stay distinct."""
    counts: dict[tuple, int] = {}
    for f in findings:
        sf = by_rel.get(f.path)
        line_text = sf.line_text(f.line) if sf is not None else ""
        key = (f.rule_id, f.path, line_text)
        occ = counts.get(key, 0)
        counts[key] = occ + 1
        f.fingerprint = _fingerprint(f, line_text, occ)


def run_analysis(
    paths: list[str] | None = None,
    root: str | None = None,
    rules: list[str] | None = None,
    baseline_path: str | None = None,
) -> AnalysisResult:
    """Run the rule set over ``paths`` (defaults from pyproject's
    ``[tool.locust-analysis]``).  Returns every finding with baselined/new
    split applied; ``result.new`` non-empty is the gate failure."""
    from locust_tpu.analysis import config as cfg
    from locust_tpu.analysis.baseline import load_baseline
    from locust_tpu.analysis.registry import get_rules

    root = os.path.abspath(root or cfg.find_root())
    conf = cfg.load_config(root)
    paths = list(paths) if paths else list(conf["paths"])
    if baseline_path is None:
        baseline_path = os.path.join(root, conf["baseline"])
    rule_objs = get_rules(rules)
    files = load_files(paths, root)
    by_rel = {f.rel: f for f in files}

    findings: list[Finding] = []
    for sf in files:
        if sf.parse_error is not None:
            findings.append(
                Finding(
                    ENGINE_RULE,
                    sf.rel,
                    sf.parse_error.lineno or 1,
                    sf.parse_error.offset or 0,
                    f"file does not parse: {sf.parse_error.msg}",
                )
            )
    parsed = [f for f in files if f.tree is not None]
    # Phase 1: one pass over the already-parsed trees builds the
    # whole-program summaries + call graph; phase 2 runs the rules.
    # Skipped entirely when no selected rule is interprocedural — the
    # single-rule dev loop (--rule R004) should not pay for summaries
    # it never reads.
    program = None
    if any(
        type(r).check_program is not Rule.check_program for r in rule_objs
    ):
        from locust_tpu.analysis.summaries import build_program

        program = build_program(parsed, root)
    rule_ms: dict[str, float] = {}
    for rule in rule_objs:
        t0 = time.perf_counter()
        for sf in parsed:
            findings.extend(rule.check_file(sf, root))
        findings.extend(rule.check_project(parsed, root))
        if program is not None:
            findings.extend(rule.check_program(program))
        rule_ms[rule.rule_id] = round(
            (time.perf_counter() - t0) * 1000.0, 1
        )

    # noqa suppression (reason mandatory; R000 is never suppressible).
    kept: list[Finding] = []
    suppressed = 0
    for f in findings:
        sf = by_rel.get(f.path)
        directive = sf.noqa.get(f.line) if sf is not None else None
        if (
            directive is not None
            and f.rule_id != ENGINE_RULE
            and f.rule_id in directive[0]
        ):
            if directive[1]:
                suppressed += 1
                continue
            kept.append(f)
            kept.append(
                Finding(
                    ENGINE_RULE,
                    f.path,
                    f.line,
                    f.col,
                    f"noqa[{f.rule_id}] has no reason — a suppression "
                    "must say why (docs/ANALYSIS.md)",
                )
            )
        else:
            kept.append(f)

    kept.sort(key=lambda f: (f.path, f.line, f.rule_id))
    _assign_fingerprints(kept, by_rel)
    known = load_baseline(baseline_path)
    for f in kept:
        # R000 (engine self-checks) is never baselineable: an unparseable
        # file or a reasonless noqa must block even if someone wrote it
        # into the baseline file by hand.
        f.baselined = f.rule_id != ENGINE_RULE and f.fingerprint in known
    new = [f for f in kept if not f.baselined]
    return AnalysisResult(
        findings=kept,
        new=new,
        suppressed=suppressed,
        n_files=len(files),
        rules=[r.rule_id for r in rule_objs],
        rule_ms=rule_ms,
    )


# ------------------------------------------------------------- changed scope


_HUNK_RE = re.compile(r"^@@ -\d+(?:,\d+)? \+(\d+)(?:,(\d+))? @@")


def changed_lines(
    root: str, ref: str = "HEAD"
) -> dict[str, set[int] | None]:
    """{repo-relative path: new-side line numbers touched (None = the
    whole file)} vs a git ref — the ``--changed`` pre-commit scope.
    Untracked (not-yet-added) files count whole-file: ``git diff`` never
    lists them, and a brand-new module silently scoped to nothing would
    be the exact trap the loud ValueError below exists to prevent.
    Raises ValueError when git cannot produce the diff (not a repo,
    unknown ref)."""
    try:
        out = subprocess.run(
            ["git", "-C", root, "diff", "--no-color", "--unified=0",
             ref, "--"],
            capture_output=True, text=True, timeout=60,
        )
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=60,
        )
    except (OSError, subprocess.SubprocessError) as e:
        raise ValueError(f"--changed needs git: {e}")
    if out.returncode != 0:
        raise ValueError(
            f"git diff {ref!r} failed: {out.stderr.strip() or out.stdout}"
        )
    changed: dict[str, set[int] | None] = {}
    current: set[int] | None = None
    for line in out.stdout.splitlines():
        if line.startswith("+++ "):
            path = line[4:].strip()
            if path.startswith("b/"):
                path = path[2:]
            if path == "/dev/null":
                current = None
            else:
                current = set()
                changed[path] = current
        elif current is not None:
            m = _HUNK_RE.match(line)
            if m:
                start = int(m.group(1))
                count = int(m.group(2)) if m.group(2) is not None else 1
                current.update(range(start, start + max(count, 1)))
    if untracked.returncode == 0:
        for path in untracked.stdout.splitlines():
            if path:
                changed[path.strip()] = None  # whole file is new
    return changed


def scope_to_changed(
    result: AnalysisResult, changed: dict[str, set[int] | None]
) -> AnalysisResult:
    """Findings restricted to lines touched by the diff.  Full-repo
    analysis already ran (fingerprints, baseline and suppression are
    whole-tree facts); this only narrows what is REPORTED/gated."""

    def hit(f: Finding) -> bool:
        if f.path not in changed:
            return False
        lines = changed[f.path]
        return lines is None or f.line in lines

    kept = [f for f in result.findings if hit(f)]
    return AnalysisResult(
        findings=kept,
        new=[f for f in kept if not f.baselined],
        suppressed=result.suppressed,
        n_files=result.n_files,
        rules=result.rules,
        rule_ms=result.rule_ms,
    )


# --------------------------------------------------------------- AST helpers
# Shared by the rule modules; kept here so each rule stays ~a screenful.


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover  # locust: noqa[R017] unparse is total on parsed trees; "" is the documented fallback and there is no logger inside the engine to record to
        return ""


def call_name(call: ast.Call) -> str:
    """Dotted name of a call's callee: ``jax.jit`` -> "jax.jit"."""
    return unparse(call.func)


def const_int(node: ast.AST) -> int | None:
    """Constant-fold an int expression over + - * << (re-spelled wire
    constants are arithmetic like ``64 * 1024 * 1024``)."""
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    if isinstance(node, ast.BinOp):
        left, right = const_int(node.left), const_int(node.right)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.LShift) and 0 <= right < 128:
            return left << right
    return None


def module_functions(tree: ast.Module) -> dict[str, list[ast.FunctionDef]]:
    """name -> every def/async def with that name anywhere in the module
    (methods and nested defs included; heuristic resolution by name)."""
    out: dict[str, list] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def emit_json(result: AnalysisResult) -> str:
    return json.dumps(result.as_dict(), indent=2, sort_keys=True)
