"""Runtime configuration for the Locust-TPU engine.

The reference (wuyan33/Locust) freezes its capacities at compile time via
``#define``s — MAX_LINES_FILE_READ=5800, EMITS_PER_LINE=20, MAX_EMITS,
GRID_SIZE/BLOCK_SIZE (reference MapReduce/src/main.cu:18-27).  On TPU, JIT
specialization replaces compile-time constants, so the same knobs live in a
runtime dataclass: each distinct config traces/compiles once and is cached.

Byte-width caps mirror the reference's fixed-width KV structs
(KeyValuePair.key[100]/value[100], KeyIntValuePair.key[30] —
reference MapReduce/src/KeyValue.h:6-18), rounded up to TPU-friendly
power-of-two widths (lane-sized multiples of 4 for uint32 key packing).
"""

from __future__ import annotations

import dataclasses


# Tokenization delimiter set — byte-for-byte the reference's strtok delimiters
# (reference MapReduce/src/main.cu:138).  This *defines* WordCount semantics
# (hyphens split words, apostrophes split contractions); see SURVEY.md Q11.
DELIMITERS: bytes = b" ,.-;:'()\"\t"

# The single source of truth for Process-stage sort strategies:
# EngineConfig validation, the CLI --sort-mode choices, and
# ops.process_stage.sort_and_compact dispatch all key off this.
SORT_MODES = (
    "hash", "hashp", "hashp2", "hashp1", "hash1", "radix", "bitonic", "lex",
    "hasht", "hasht-mxu", "fused",
)

# The sort-FREE fold family (ops/hash_table.py): identical probe/exactness
# ladder, differing only in how the value-combine scatter is spelled —
# "hasht" = XLA duplicate-index scatter, "hasht-mxu" = one-hot bf16
# contraction on the MXU (hash_table.mxu_scatter_add), "fused" = hasht
# semantics everywhere PLUS the Pallas map->aggregate megakernel
# (ops/pallas/fused_fold.py) at the single-device line->fold boundary,
# which pre-aggregates each block in VMEM so the [lines, emits, key_width]
# token tensor never round-trips HBM.  Every site that used to test
# ``sort_mode == "hasht"`` must test membership here instead; the three
# modes share slot-ordered (non prefix-compact) table semantics and
# bit-identical tables (tests/test_hasht_mxu.py, tests/test_fused_fold.py).
HASHT_FAMILY = ("hasht", "hasht-mxu", "fused")


def default_sort_mode(backend: str) -> str:
    """Measured per-backend default Process strategy.

    CPU: "hasht" wins the driver-policy grid decisively
    (artifacts/bench_block_cpu_r4.jsonl: 7.94 vs hash1's 5.14 MB/s) and
    is soak-proven (260-case battery).  TPU: "hashp2" per the committed
    engine-level on-hardware A/B (artifacts/tpu_runs.jsonl
    engine_sort_mode_ab 2026-07-31: 57.6 vs hashp's 56.9 MB/s — within
    single-window noise, so the static default simply follows the
    committed measurement; bench.py's evidence tuning supersedes this
    with the latest engine-level A/B row at bench time).  Anything
    else: the portable "hash".
    """
    return {"cpu": "hasht", "tpu": "hashp2"}.get(backend, "hash")

# Newline bytes also terminate tokens: the reference tokenizes line-by-line so
# a '\n' never reaches strtok; our padded line tensors strip newlines at ingest.
PAD_BYTE: int = 0

# Bytes that are token boundaries on DEVICE beyond the strtok set: NUL (row
# padding / embedded NULs) and the newline pair.  The single source for
# every host-side measure that must count tokens the device's way
# (core/bytes_ops.delimiter_mask, io/loader.measure_caps*) — three drifting
# copies of this literal would let --auto-caps under-size emits_per_line.
TOKEN_BOUNDARY_EXTRA: bytes = b"\x00\n\r"
FULL_DELIMITERS: bytes = DELIMITERS + TOKEN_BOUNDARY_EXTRA


# Bitonic Pallas sort tile (rows of 128 lanes; ops/pallas/sort.py).
# Parsed + validated HERE (jax-free) so both the kernel and the roofline
# model (utils/roofline.py) read the one value — a drifted copy would
# silently model the wrong HBM pass count.  Bigger tiles trade fewer HBM
# round-trips for larger VMEM residency and longer unrolled kernels; the
# on-hardware sweep (scripts/tpu_checks.py bitonic_tile_ab) measures the
# knee.
import os as _os


def machine_cache_dir(tag: str = "") -> str:
    """A /tmp jax compilation-cache dir keyed to THIS machine's CPU.

    The persistent cache stores CPU AOT executables compiled for the exact
    host feature set; the driver/bench/sweep processes can run on hosts
    with different CPUs across sessions, and XLA loading a foreign entry
    warns about (and risks) SIGILL.  Keying the directory by the host's
    cpuinfo flags makes a foreign machine miss instead of loading a
    mismatched executable.  jax-free so every entrypoint can call it
    before its first ``import jax``.

    Purge-on-mismatch (VERDICT r5 item 7): the name-level keying alone did
    NOT keep the round-5 driver bench free of XLA's feature-mismatch
    SIGILL warning — a /tmp dir can survive onto a host whose flags line
    hashes the same 10-hex prefix, or carry entries from before the keying
    existed.  So the dir now also holds a ``HOST_FEATURES`` stamp with the
    FULL feature key: a dir whose stamp is absent-but-nonempty or differs
    from this host is wiped before use, making a foreign AOT entry a cache
    MISS instead of a load-with-warning.  Best-effort (concurrent callers
    race benignly: the stamp write is atomic-rename and cache entries are
    re-creatable).
    """
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            info = f.read()
        key = next(
            (ln for ln in info.splitlines() if ln.startswith("flags")), info
        )
    except OSError:  # pragma: no cover - non-Linux fallback
        key = " ".join(_os.uname())
    h = hashlib.sha1(key.encode()).hexdigest()[:10]
    d = f"/tmp/jax_comp_cache_{h}{tag}"
    try:
        _stamp_or_purge(d, key)
    except OSError:  # pragma: no cover - cache dir is best-effort
        pass
    return d


def _stamp_or_purge(d: str, key: str) -> None:
    """Ensure ``d`` exists and carries a ``HOST_FEATURES`` stamp matching
    ``key``; entries written under any OTHER feature set are purged first
    (a stale entry only costs a recompile; loading it risks SIGILL)."""
    import shutil

    stamp = _os.path.join(d, "HOST_FEATURES")
    try:
        with open(stamp) as f:
            if f.read() == key:
                return
        mismatch = True
    except OSError:
        # No stamp: a legacy/foreign dir with entries must be treated as
        # mismatched; an empty or absent dir just needs stamping.
        try:
            mismatch = bool(_os.listdir(d))
        except OSError:
            mismatch = False
    if mismatch:
        shutil.rmtree(d, ignore_errors=True)
    _os.makedirs(d, exist_ok=True)
    tmp = stamp + f".tmp.{_os.getpid()}"
    with open(tmp, "w") as f:
        f.write(key)
    _os.replace(tmp, stamp)


# Probe rounds of the sort-free hash-table aggregation (sort_mode="hasht",
# ops/hash_table.py) before a row falls back to the exact sort path.
# jax-free HERE so utils/roofline.py can model the pass count without
# importing the kernel module.
HASHT_PROBES: int = int(_os.environ.get("LOCUST_HASHT_PROBES", 4))
if HASHT_PROBES < 1:
    raise ValueError(f"LOCUST_HASHT_PROBES must be >= 1, got {HASHT_PROBES}")

# MXU histogram geometry for the "hasht-mxu" combine scatter
# (ops/hash_table.mxu_scatter_add): the slot id decomposes as
# ``hi * HASHT_MXU_LANES + lo`` and the per-slot sums come out of
# ``[t_hi, n] x [n, t_lo]`` bf16 contractions.  512 lanes (a multiple of
# the 128-wide MXU/VPU tile) matches the measured K_mxu_hist probe
# (scripts/bench_sort_variants.py variant_k: 65536 buckets as [128, 512],
# 52.0 ms / 1.6 s compile on v5e, ledger ts 1785523898).  jax-free here so
# utils/roofline.py models the one-hot traffic off the same numbers the
# kernel runs with.
HASHT_MXU_LANES: int = int(_os.environ.get("LOCUST_HASHT_MXU_LANES", 512))
if HASHT_MXU_LANES < 1:
    raise ValueError(
        f"LOCUST_HASHT_MXU_LANES must be >= 1, got {HASHT_MXU_LANES}"
    )

# Rows per one-hot chunk: the [chunk, t_hi]+[chunk, t_lo] bf16 one-hot
# operands are materialized per chunk (lax.scan over chunks), bounding the
# transient at ~chunk*(t_hi+t_lo)*2 bytes instead of scaling with the
# whole fold's n.  The cap also carries an EXACTNESS bound: per-chunk
# partial sums accumulate in fp32, and 8-bit value limbs stay exact there
# while a slot's per-chunk partial < 2^24, i.e. chunk <= 2^24/255 = 65793.
HASHT_MXU_CHUNK: int = int(_os.environ.get("LOCUST_HASHT_MXU_CHUNK", 32768))
if not 1 <= HASHT_MXU_CHUNK <= 65536:
    raise ValueError(
        "LOCUST_HASHT_MXU_CHUNK must be in [1, 65536] (fp32 partial-sum "
        f"exactness bound 2^24/255), got {HASHT_MXU_CHUNK}"
    )


def hasht_mxu_grid(table_size: int) -> tuple[int, int]:
    """[t_hi, t_lo] histogram grid covering ``table_size`` slots.

    The ONE place the decomposition is decided: ops/hash_table.py runs it
    and utils/roofline.py prices its one-hot operands, so the modeled
    traffic cannot drift from what the contraction actually reads.  Grid
    cells at/above table_size are never addressed (slot ids are < T) and
    simply stay zero."""
    t_lo = min(HASHT_MXU_LANES, table_size)
    t_hi = -(-table_size // t_lo)
    return t_hi, t_lo


# --- fused map->aggregate megakernel knobs (ops/pallas/fused_fold.py) ---
# jax-free HERE so utils/roofline.py prices the kernel's HBM bytes off the
# SAME validated values the kernel runs with (the hasht-mxu precedent: a
# drifted copy would silently model the wrong traffic).

# Lines per kernel grid step.  uint8 VMEM tiles are (32, 128), so the tile
# must be a multiple of 32; each step's within-tile dedupe builds a
# [tile*emits_per_line]^2 Gram matrix in VMEM, which is what keeps the
# default small (32 lines x 20 emits = a 640^2 f32 Gram, ~1.6 MB).
FUSED_TILE_LINES: int = int(_os.environ.get("LOCUST_FUSED_TILE_LINES", 32))
if FUSED_TILE_LINES < 32 or FUSED_TILE_LINES % 32 != 0:
    raise ValueError(
        f"LOCUST_FUSED_TILE_LINES must be a positive multiple of 32 "
        f"(uint8 sublane tile), got {FUSED_TILE_LINES}"
    )

# VMEM-resident kernel table slots (per BLOCK, rebuilt every fold): bounds
# the distinct keys one block can pre-aggregate in VMEM; keys past it
# strand to the residual stream (and a residual overflow falls the whole
# block back to the stock hasht fold — exact either way).  Power of two so
# the in-kernel ``h % slots`` is a bitwise AND.  8192 slots x (key bytes +
# occupied + count) f32 planes ~ 1.2 MB VMEM at key_width 32.
FUSED_TABLE_SLOTS: int = int(_os.environ.get("LOCUST_FUSED_TABLE_SLOTS", 8192))
if FUSED_TABLE_SLOTS < 512 or FUSED_TABLE_SLOTS & (FUSED_TABLE_SLOTS - 1):
    raise ValueError(
        f"LOCUST_FUSED_TABLE_SLOTS must be a power of two >= 512, "
        f"got {FUSED_TABLE_SLOTS}"
    )

# Residual rows per grid tile: per-tile distinct keys the probe rounds
# strand (table collision/full) stream out through this bounded buffer;
# more than this per tile sets the kernel's overflow flag and the engine
# re-folds the block through the stock path.  Power of two.
FUSED_RESIDUAL_ROWS: int = int(
    _os.environ.get("LOCUST_FUSED_RESIDUAL_ROWS", 32)
)
if FUSED_RESIDUAL_ROWS < 8 or FUSED_RESIDUAL_ROWS & (FUSED_RESIDUAL_ROWS - 1):
    raise ValueError(
        f"LOCUST_FUSED_RESIDUAL_ROWS must be a power of two >= 8, "
        f"got {FUSED_RESIDUAL_ROWS}"
    )

# Residual row padding lanes beyond the key bytes (count + valid flag +
# zero tail): the kernel's residual rows are (key_width + FUSED_RESID_PAD)
# f32 lanes wide, and those rows DO cross HBM — utils/roofline.py prices
# exactly this width off this constant.
FUSED_RESID_PAD: int = 8

# Off-TPU the kernel runs in interpret mode (the pinned test vehicle —
# NEVER inside a full CPU mesh program, CLAUDE.md); the interpreter
# re-traces the kernel body per grid step, so production block sizes cost
# minutes of XLA CPU compile.  Blocks with more lines than this take the
# hasht-identical stock path off-TPU with a one-time notice — the same
# stance as BITONIC_INTERPRET_MAX.  On TPU the Mosaic kernel always runs.
FUSED_INTERPRET_MAX_LINES: int = int(
    _os.environ.get("LOCUST_FUSED_INTERPRET_MAX_LINES", 8192)
)
if FUSED_INTERPRET_MAX_LINES < 0:
    raise ValueError(
        f"LOCUST_FUSED_INTERPRET_MAX_LINES must be >= 0, "
        f"got {FUSED_INTERPRET_MAX_LINES}"
    )


# f32 sublane tile rows: the kernel stores its table as stacked
# [t_hi, t_lo] planes and slices them per plane, so the plane stride
# (t_hi) must stay sublane-aligned for Mosaic; fused_table_layout pads
# small tables up to this.  Shared here (jax-free) so the kernel and the
# roofline model read ONE value.
FUSED_SUBLANE: int = 8


def fused_grid(slots: int | None = None) -> tuple[int, int]:
    """[t_hi, t_lo] LOGICAL decomposition of a ``slots``-slot kernel
    table's slot axis (default FUSED_TABLE_SLOTS; t_hi * t_lo == slots;
    slot = hi * t_lo + lo).

    t_lo is fixed at the 512-lane width the MXU histogram measured best
    (NOT the HASHT_MXU_LANES env knob: the kernel's hi/lo split is
    shift+mask, so t_lo must stay a power of two).  The ONE place the
    decomposition is decided: :func:`fused_table_layout` (the physical
    plane layout) derives from it, so the two can never drift."""
    s = FUSED_TABLE_SLOTS if slots is None else slots
    t_lo = min(512, s)
    t_hi = s // t_lo
    return t_hi, t_lo


# Blocks folded per PERSISTENT-KERNEL segment (megakernel v2 streaming
# formulation, ops/pallas/fused_fold.py).  run_stream groups this many
# staged blocks into ONE kernel launch whose table planes stay VMEM-
# resident across the whole segment, amortizing the per-block
# acc->settle->acc HBM round-trip by this factor.  Clamped at runtime by
# :func:`fused_stream_seg_blocks` (f32 count-plane exactness + off-TPU
# interpret-cost caps), so a large value is safe — it just saturates the
# clamp.
FUSED_STREAM_BLOCKS: int = int(
    _os.environ.get("LOCUST_FUSED_STREAM_BLOCKS", 8)
)
if FUSED_STREAM_BLOCKS < 1:
    raise ValueError(
        f"LOCUST_FUSED_STREAM_BLOCKS must be >= 1, got {FUSED_STREAM_BLOCKS}"
    )


def fused_stream_seg_blocks(
    emits_per_block: int, block_lines: int, on_tpu: bool
) -> int:
    """Blocks per persistent-kernel streaming segment, clamped for
    exactness and interpret cost.

    The kernel counts in f32 planes, exact only below 2**24, and the
    per-segment emit budget is ``seg_blocks * emits_per_block`` — so the
    segment is clamped to keep that product under 2**24 (the same bound
    fused_engine_eligible enforces per block).  Off-TPU the interpreter
    re-traces per grid step, so the segment additionally respects
    FUSED_INTERPRET_MAX_LINES over its total line count.  jax-free so
    utils/roofline.py amortizes the v2 stream model off the SAME clamp
    the engine runs with."""
    cap = max(1, ((1 << 24) - 1) // max(1, emits_per_block))
    seg = min(FUSED_STREAM_BLOCKS, cap)
    if not on_tpu and block_lines > 0:
        seg = min(seg, max(1, FUSED_INTERPRET_MAX_LINES // block_lines))
    return max(1, seg)


def fused_table_layout(slots: int | None = None) -> tuple[int, int]:
    """[t_hi, t_lo] PHYSICAL plane layout for a ``slots``-slot kernel
    table (default FUSED_TABLE_SLOTS): the :func:`fused_grid`
    decomposition with the hi axis padded up to FUSED_SUBLANE so
    per-plane ref slices stay Mosaic-aligned.  The megakernel allocates
    its VMEM planes from this and utils/roofline.py prices the table
    flush off it, so the modeled bytes cannot drift from the table that
    actually crossed HBM (the hasht_mxu_grid contract).  Padded slots
    are never addressed (slot ids < slots) and decode as count-0 =
    invalid."""
    t_hi, t_lo = fused_grid(slots)
    return max(FUSED_SUBLANE, t_hi), t_lo


BITONIC_TILE_ROWS: int = int(_os.environ.get("LOCUST_BITONIC_TILE_ROWS", 256))
if BITONIC_TILE_ROWS < 8 or BITONIC_TILE_ROWS & (BITONIC_TILE_ROWS - 1):
    raise ValueError(
        f"LOCUST_BITONIC_TILE_ROWS must be a power of two >= 8 "
        f"(int32 min sublane tile), got {BITONIC_TILE_ROWS}"
    )

# Cap on compare-exchange substages statically unrolled into ONE Pallas
# launch.  Unlimited fusion (the round-4 first cut) produced a ~120-substage
# kernel whose Mosaic compile crashed axon's remote tpu_compile_helper
# (HTTP 500, measured on v5e 2026-07-31); capping trades extra HBM
# round-trips for a compilable kernel.  0 = unlimited.  The DEFAULT is
# capped (32: ~4 launches for the 120-substage first stage block) so the
# next hardware attempt runs the mitigation, not the known-crashing
# schedule; scripts/tpu_checks.py's bitonic_fused_ab ladder measures
# unlimited fusion alongside, so the cap can be raised the moment
# hardware shows the int32-mask rewrite alone fixed the Mosaic crash.
BITONIC_MAX_FUSED: int = int(_os.environ.get("LOCUST_BITONIC_MAX_FUSED", 32))
if BITONIC_MAX_FUSED < 0:
    raise ValueError(
        f"LOCUST_BITONIC_MAX_FUSED must be >= 0, got {BITONIC_MAX_FUSED}"
    )


def _pack_local_stages(specs, max_fused):
    """Split/merge tile-local stage specs ``(s, t_hi, t_lo)`` into launches
    of at most ``max_fused`` substages each (greedy, order-preserving;
    stages split mid-run when needed)."""
    launches, cur, cnt = [], [], 0
    for s, t_hi, t_lo in specs:
        t = t_hi
        while t >= t_lo:
            if cnt == max_fused:
                launches.append(tuple(cur))
                cur, cnt = [], 0
            take = min(max_fused - cnt, t - t_lo + 1)
            cur.append((s, t, t - take + 1))
            cnt += take
            t -= take
    if cur:
        launches.append(tuple(cur))
    return launches


def bitonic_schedule(kbits: int, m: int, max_fused: int | None = None):
    """HBM-pass schedule of the Pallas bitonic sort for ``n = 2^kbits``
    elements with tile ``2^m``: a list of ``("local", ((s, t_hi, t_lo), ...))``
    fused-kernel launches and ``("cross", s, t)`` single XLA passes, in
    execution order.  The ONE place the launch structure is decided —
    ops/pallas/sort.py executes it and utils/roofline.py counts it, so the
    modeled pass count can't drift from what the kernel actually does."""
    mf = BITONIC_MAX_FUSED if max_fused is None else max_fused
    if mf <= 0:
        mf = 1 << 30
    sched = []
    local1 = [(s, s, 1) for s in range(1, min(kbits, m) + 1)]
    for ch in _pack_local_stages(local1, mf):
        sched.append(("local", ch))
    for s in range(m + 1, kbits + 1):
        for t in range(s, m, -1):
            sched.append(("cross", s, t))
        for ch in _pack_local_stages([(s, m, 1)], mf):
            sched.append(("local", ch))
    return sched


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static shape/capacity configuration of one MapReduce pipeline.

    Frozen + hashable so it can be a ``jax.jit`` static argument.
    """

    # Max bytes per input line (value side). Reference: char value[100]
    # (KeyValue.h:9) → rounded to 128 for TPU lane alignment.
    line_width: int = 128

    # Max bytes per emitted key. Reference: char key[30] (KeyValue.h:15) →
    # rounded to 32 (8 uint32 big-endian lanes).
    key_width: int = 32

    # Max emits (tokens) per line. Reference: EMITS_PER_LINE=20 (main.cu:19).
    emits_per_line: int = 20

    # Lines per processing block. Reference caps the whole file at
    # MAX_LINES_FILE_READ=5800 (main.cu:18); we instead stream fixed-size
    # blocks so there is no global cap (SURVEY.md §5 "long-context").
    block_lines: int = 4096

    # Accumulator table capacity: distinct keys tracked across blocks.
    # Bounds the cross-block merge cost (the merge sorts table_size +
    # emits_per_block rows, not 2 x emits_per_block); a corpus with more
    # distinct keys than this reports truncation (RunResult.truncated).
    # None (default) resolves to min(65536, max(emits_per_block, 4096))
    # (see resolved_table_size for the floor's rationale) — measured the
    # fastest setting at both 5k and 100k vocabularies
    # (artifacts/bench_table_size_cpu_r2.jsonl); vocabularies past 2^16
    # distinct keys must raise it explicitly (tests/test_scale.py pins the
    # loud-truncation behavior at the default).
    table_size: int | None = None

    # Process-stage sort strategy.  "hash": sort by a 64-bit key hash —
    # 3 sort operands + one index payload + gather, ~2x faster per sort and
    # ~6x faster to compile than full-key sort; equal keys still group
    # adjacently (exact-key segment boundaries downstream), device order is
    # hash order (host output re-sorts).  "hashp": same 3 hash keys but the
    # row rides as sort PAYLOAD operands instead of a post-sort gather —
    # 19% faster on TPU v5e at 720k rows (the gather's random HBM reads
    # cost more than payload carriage).  "hashp2": payload carriage with
    # only 2 key operands (validity folded into a 31-bit primary hash, h2
    # tiebreak).  "hash1": ONE 32-bit sort operand (31 hash bits +
    # validity bit) + gather — the CPU winner; collisions only duplicate a
    # table row, re-merged downstream (process_stage._folded_key).
    # "radix": same folded key sorted by O(n) LSD radix passes instead of
    # the comparison network (ops/radix_sort.py; loses 2.5-3x on TPU).
    # "bitonic": hand-written Pallas bitonic network (ops/pallas/sort.py)
    # over the folded key with payload carriage — tile-local compare
    # passes fused in VMEM, ~10x fewer HBM round-trips than the stock
    # network's operand streaming; interpret mode off-TPU.
    # "lex": sort full big-endian key lanes — exact lexicographic device
    # order, the reference's KIVComparator semantics (KeyValue.h:20-33).
    # "hasht": the fold-level SORT-FREE hash-table aggregation
    # (ops/hash_table.py) — probe/claim/verify scatters with an exact
    # sort fallback ladder; the measured CPU default.  "hasht-mxu": the
    # same fold with the value-combine scatter spelled as a one-hot bf16
    # MXU contraction (hash_table.mxu_scatter_add) instead of XLA's
    # duplicate-index scatter — byte-identical tables, armed for the TPU
    # engine-level A/B (the K_mxu_hist primitive measured 52.0 ms vs the
    # J scatter's 107.6 at the fold shape, ledger ts 1785523898).
    # "fused": hasht semantics PLUS the Pallas map->aggregate megakernel
    # (ops/pallas/fused_fold.py) at the single-device line->fold
    # boundary — tokenize + hash + table-update in one VMEM-resident
    # kernel, so the [lines, emits, key_width] token tensor never
    # round-trips HBM; tables stay BIT-identical to "hasht" (the
    # settlement fold is hasht's own aggregate_exact).  Off the
    # wordcount map / off supported shapes / inside mesh programs the
    # mode degrades to "hasht" exactly.  Variant timings:
    # scripts/bench_sort_variants.py -> artifacts/.
    sort_mode: str = "hash"

    # Overflow behavior for > emits_per_line tokens: the reference prints
    # "WARN: Exceeded emit limit" and drops (main.cu:141-144). We drop
    # silently on device and surface a host-side overflow count.
    warn_on_overflow: bool = True

    # Use Pallas kernels for the map/reduce hot loops where available;
    # otherwise pure-jnp/XLA lowering.
    use_pallas: bool = False

    # Map-stage key extraction: "einsum" contracts the one-hot start mask
    # against shifted byte planes on the MXU (the gather-as-matmul trick —
    # the TPU winner, where scalar gathers are ~12x slower); "gather" is a
    # plain scatter-starts + take_along_axis (the CPU winner: the einsum
    # does L*W*E*K multiply-adds a CPU has no systolic array to hide —
    # ~36ms vs ~2ms at 700 hamlet lines, VERDICT r3 weak #4).  "auto"
    # resolves per backend at trace time: einsum on TPU, gather elsewhere.
    map_impl: str = "auto"

    # --- zero-stall streaming executor knobs (docs/DESIGN.md) ---------
    # Donate the fold accumulator into each per-block dispatch
    # (jax.jit donate_argnums): XLA aliases the hash-table buffers
    # input->output so the largest live array is updated in place
    # instead of re-allocated per fold.  Applies to the per-block fold
    # AND the one-dispatch lax.scan path; escape hatch for callers that
    # hold references to a pre-fold accumulator.
    donate_fold: bool = True

    # Move checkpoint snapshots to a bounded background writer
    # (io/snapshot.py): the fold loop only marks a generation (an
    # on-device table copy, async) and the writer thread does the
    # device->host copy + npz write + atomic rename off the critical
    # path, latest-wins when the loop laps it.  False restores the
    # synchronous in-loop save (identical on-disk format either way).
    async_checkpoint: bool = True

    # Reuse a ring of STREAM_DISPATCH_DEPTH+1 pre-allocated host staging
    # buffers for run_stream's per-block pad+transfer instead of a fresh
    # numpy allocation per block — allocation-free steady state, and the
    # ring size is exactly what the bounded-inflight backpressure
    # guarantees is no longer referenced by an in-flight fold.
    stream_staging_ring: bool = True

    # Structured telemetry opt-in (locust_tpu.obs, docs/OBSERVABILITY.md):
    # True enables the process tracer at engine construction, so API
    # users get spans/metrics without touching the obs module (the CLI's
    # --trace-out sets the same switch and adds the export).  Default
    # False = the zero-overhead no-op path; note the knob is part of the
    # config repr, so flipping it (like any config change) starts
    # checkpointed runs fresh.
    trace: bool = False

    def __post_init__(self):
        if self.key_width <= 0 or self.key_width % 4 != 0:
            raise ValueError("key_width must be a positive multiple of 4 (uint32 lanes)")
        if self.line_width <= 0 or self.emits_per_line <= 0 or self.block_lines <= 0:
            raise ValueError("line_width, emits_per_line, block_lines must be positive")
        if self.table_size is not None and self.table_size <= 0:
            raise ValueError("table_size must be positive")
        if self.sort_mode not in SORT_MODES:
            raise ValueError(
                f"sort_mode must be one of {SORT_MODES}, got {self.sort_mode!r}"
            )
        if self.map_impl not in ("auto", "einsum", "gather"):
            raise ValueError(
                "map_impl must be 'auto', 'einsum', or 'gather', "
                f"got {self.map_impl!r}"
            )

    @property
    def key_lanes(self) -> int:
        """Number of uint32 big-endian lanes a packed key occupies."""
        return self.key_width // 4

    def fingerprint(self) -> str:
        """Stable digest of EVERY config field — the executable-identity
        half of the serve tier's warm-cache key (docs/SERVING.md): two
        configs share a compiled program iff their fingerprints match.
        Built on ``repr`` of the frozen dataclass (field order is the
        class definition, values are literals), the same identity the
        checkpoint fingerprints already ride (``run_stream`` embeds
        ``repr(cfg)``), so "same executable" and "same checkpoint
        lineage" can never disagree about what a config IS.  Memoized:
        the serve scheduler keys every pending job by it on every poll
        tick, and a frozen config's identity never changes."""
        fp = self.__dict__.get("_fingerprint")
        if fp is None:
            import hashlib

            fp = hashlib.sha1(repr(self).encode()).hexdigest()[:12]
            object.__setattr__(self, "_fingerprint", fp)
        return fp

    @property
    def emits_per_block(self) -> int:
        """Emit-table rows per block (analog of MAX_EMITS, main.cu:20)."""
        return self.block_lines * self.emits_per_line

    @property
    def resolved_table_size(self) -> int:
        """Accumulator capacity with the None default resolved.

        ``min(65536, emits_per_block)`` measured fastest at bench shapes
        (artifacts/bench_table_size_cpu_r2.jsonl), but the 4096 FLOOR is
        a usability guard the round-4 batteries earned three times over:
        the table is CORPUS-level state, and a small block size (e.g.
        block_lines=4 -> 32 emits) used to cap the entire vocabulary at
        32 keys — loudly, per contract, but on completely ordinary
        inputs.  The floor costs ~150KB and binds only where
        emits_per_block < 4096, far below any tuned shape."""
        if self.table_size is not None:
            return self.table_size
        return min(1 << 16, max(self.emits_per_block, 4096))


DEFAULT_CONFIG = EngineConfig()
