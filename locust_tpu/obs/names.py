"""The closed telemetry name registry — every span, instant event and
metric the framework can emit, in ONE dict literal.

Closed-registry stance (same as ``faultplan.SITES`` and the analysis rule
table): a typo'd name at an emission site must fail LOUDLY — at runtime
(``Tracer``/``Metrics`` validate against this dict when telemetry is
enabled) and statically (analysis rule R009 checks both directions: every
``obs.span``/``obs.event``/``obs.metric_*`` literal exists here, and
every entry here is emitted somewhere under ``locust_tpu/``).  A name
nobody validates is a timeline nobody can correlate.

Emission convention (what R009 can see): emit through the ``obs`` module
functions with a literal name — ``obs.span("engine.stage.map")``, never a
name built at runtime.  Kinds: ``span`` (duration), ``event`` (instant),
``counter``/``gauge``/``histogram`` (metrics).
"""

from __future__ import annotations

NAMES = {
    # --- spans (durations) -------------------------------------------
    "job.run": "span",              # master: one distributor job end-to-end
    "master.map_rpc": "span",       # master: one shard map attempt RPC
    "master.fetch": "span",         # master: one intermediate transfer
    "worker.map": "span",           # worker: one map command (runner incl.)
    "cli.load": "span",             # CLI: corpus ingest
    "cli.run": "span",              # CLI: the engine run
    "cli.output": "span",           # CLI: table print / intermediate write
    "engine.stage.map": "span",     # timed_run Map stage (per block)
    "engine.stage.process": "span", # timed_run Process stage (per block)
    "engine.stage.reduce": "span",  # timed_run Reduce stage (per block)
    "engine.stage.merge": "span",   # timed_run cross-block table merge
    "stream.block": "span",         # run_stream: stage+dispatch of one block
    "ckpt.write": "span",           # async writer: serialize+publish one gen
    "serve.queue_wait": "span",     # serve: dispatcher waiting on the queue
    "serve.compile_or_hit": "span", # serve: warm-executable cache lookup/build
    "serve.dispatch": "span",       # serve: one coalesced batch dispatch
    "serve.place": "span",          # serve: pool placement decision (pool.py)
    "serve.demux": "span",          # serve: per-job result split + store
    "serve.ship": "span",           # serve: one WAL ship/catch-up RPC (replicate.py)
    "plan.optimize": "span",        # plan: the rewrite pass (optimize.py)
    "plan.compile": "span",         # plan: DAG lowering onto the engine
    "plan.run": "span",             # plan: one compiled-plan execution
    "plan.stage": "span",           # plan: one distributed stage RPC (both sides)
    "plan.shuffle": "span",         # plan: one cross-worker partition transfer
    # --- instant events ----------------------------------------------
    "fault.injected": "event",      # a faultplan rule fired (site, action)
    "ckpt.mark": "event",           # fold loop marked a snapshot generation
    "ckpt.publish": "event",        # finalize_snapshot atomic rename landed
    "ckpt.skip": "event",           # latest-wins replaced a pending mark
    "stream.stall": "event",        # bounded-inflight backpressure sync
    "obs.device_join": "event",     # xplane family times joined onto a stage
    "serve.admit": "event",         # serve: job admitted to the queue
    "serve.reject": "event",        # serve: admission rejected (reason code)
    "serve.retry": "event",         # serve: failed dispatch requeued w/ backoff
    "serve.replay": "event",        # serve: journal replay summary at startup
    "serve.takeover": "event",      # serve: role change (promotion / demotion)
    "backend.breaker_open": "event",       # breaker tripped: primary ineligible
    "backend.breaker_half_open": "event",  # cooldown over: one probe allowed
    "backend.breaker_close": "event",      # probe succeeded: primary restored
    "backend.failover": "event",    # run resumed from checkpoint on fallback
    # --- metrics ------------------------------------------------------
    "job.workers": "gauge",         # cluster size of the running job
    "stream.blocks": "counter",     # blocks folded by run_stream
    "stream.stall_ms": "histogram", # per-sync backpressure stall
    "ckpt.marks": "counter",        # snapshot generations marked
    "fault.injections": "counter",  # faults injected across all sites
    "fetch.bytes": "counter",       # intermediate payload bytes fetched
    "fetch.mb_s": "histogram",      # per-fetch payload throughput
    "serve.jobs": "counter",        # serve: jobs completed by the daemon
    "serve.latency_ms": "histogram",  # serve: per-job submit->done latency
    "serve.exec_cache_hits": "counter",    # warm-executable cache hits
    "serve.exec_cache_misses": "counter",  # ... and compiles/builds paid
    "serve.result_cache_hits": "counter",  # result cache answered a submit
    "serve.affinity_hits": "counter",      # pool placements on the warm worker
    "serve.journal_ms": "histogram",  # per-append journal write latency
    "serve.ship_lag": "gauge",      # replication lag in unacked WAL records
    "backend.breaker_trips": "counter",  # closed->open breaker transitions
    "plan.partition_bytes": "counter",  # published shuffle-partition bytes
    "plan.recomputes": "counter",   # plan stages recomputed after a failure
    "plan.speculated": "counter",   # speculative backup stage attempts
    "plan.rewrites": "counter",     # optimizer rewrites applied (optimize.py)
    "plan.subcache_hits": "counter",    # sub-plan result cache hits
    "plan.subcache_misses": "counter",  # ... and fold recomputes paid
    "plan.solo_fallbacks": "counter",   # plan jobs demoted to the solo engine
    "plan.map_warm_hits": "counter",    # map stages on warm fold-node executables
}

METRIC_KINDS = ("counter", "gauge", "histogram")


def check(name: str, kind: str) -> None:
    """Loud closed-registry validation (enabled-path only)."""
    got = NAMES.get(name)
    if got is None:
        raise ValueError(
            f"telemetry name {name!r} is not in the obs NAMES registry "
            "(locust_tpu/obs/names.py) — register it; a typo'd name "
            "records nothing the timeline can correlate"
        )
    if got != kind:
        raise ValueError(
            f"telemetry name {name!r} is registered as a {got}, "
            f"emitted as a {kind} — kind mismatch"
        )
