"""Process-wide structured tracer: nested named spans + instant events,
exported as Chrome-trace/Perfetto JSON.

The reference's entire observability is three chrono spans printed with a
UB printf (reference MapReduce/src/main.cu:405-468, SURVEY.md Q7); our
repro had outgrown that into fragments (SpanTimer wall spans, xplane
parsing, per-shard stats, stream stall accounting) that never composed
into one timeline.  This module is the one timeline:

  * spans are wall-clock durations (``time.time`` epoch, so cross-node
    merge is a clock-offset shift, not a clock translation), recorded as
    Chrome ``"ph": "X"`` complete events; instants are ``"ph": "i"``;
  * a span may carry ``sync_refs`` — device arrays blocked on at span
    EXIT, reusing SpanTimer's sync-at-exit semantics (jax imported
    lazily and only then: the tracer itself is jax-free so every
    entrypoint can import it before backend selection);
  * names are validated against the closed registry
    (``locust_tpu.obs.names``) — a typo'd name raises, enabled-path only;
  * ``serialize()``/``ingest()`` move span lists across the distributor
    wire: a worker runs its map under a request-scoped tracer, ships the
    span list back inside the map reply, and the master ``ingest``s it
    shifted by the estimated clock offset into one merged timeline
    (each remote process gets its own Chrome pid + process_name).

Thread-safe: spans/events append under one lock; tids are per-thread
Chrome thread ids.  All methods are cheap relative to what they measure
(device dispatches, RPCs); the ZERO-overhead disabled path lives in
``locust_tpu.obs.__init__`` (module hooks bail before reaching here).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from locust_tpu.obs import names as _names


class _NullSpan:
    """Shared no-op context manager: the disabled fast path allocates
    nothing (``obs.span`` returns this singleton)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """One open span; records a complete ("X") event at exit."""

    __slots__ = ("_tracer", "_name", "_sync", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, sync, args: dict):
        self._tracer = tracer
        self._name = name
        self._sync = sync
        self._args = args

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        if self._sync:
            import jax  # lazy: sync-at-exit is opt-in, tracer stays jax-free

            for ref in self._sync:
                jax.block_until_ready(ref)  # locust: noqa[R003] profiler span boundary: the sync IS the measurement
        self._tracer._complete(
            self._name, self._t0, time.time() - self._t0, self._args
        )
        return False


class Tracer:
    """Structured span/event recorder for ONE process (or one request).

    ``trace_id`` correlates records across nodes: the master stamps it
    into map requests, workers open their request tracer with it, and the
    shipped span lists merge back under the one id.
    """

    def __init__(self, trace_id: str | None = None, process: str = "main"):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.process = process
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._pids: dict[str, int] = {process: 0}
        self._tids: dict[int, int] = {}
        self._meta_process(0, process)

    # ------------------------------------------------------------ recording

    def span(self, name: str, *sync_refs, **args) -> _Span:
        _names.check(name, "span")
        return _Span(self, name, sync_refs, args)

    def event(self, name: str, **args) -> None:
        _names.check(name, "event")
        with self._lock:
            self._events.append(
                {
                    "name": name,
                    "cat": "locust",
                    "ph": "i",
                    "s": "t",
                    "ts": round(time.time() * 1e6, 1),
                    "pid": 0,
                    "tid": self._tid_locked(),
                    "args": args,
                }
            )

    def _complete(self, name: str, t0: float, dur_s: float, args: dict):
        with self._lock:
            self._events.append(
                {
                    "name": name,
                    "cat": "locust",
                    "ph": "X",
                    "ts": round(t0 * 1e6, 1),
                    "dur": round(dur_s * 1e6, 1),
                    "pid": 0,
                    "tid": self._tid_locked(),
                    "args": args,
                }
            )

    def event_count(self) -> int:
        """Current record count — a position marker for ``annotate``'s
        ``since`` (so a join can target only records a specific run
        appended)."""
        with self._lock:
            return len(self._events)

    def annotate(self, name: str, extra: dict, since: int = 0) -> int:
        """Merge ``extra`` into the args of every span/event named
        ``name`` recorded at position >= ``since`` (the device-time join
        point — ``since`` keeps a capture's measurements off spans from
        earlier, unprofiled runs); returns how many records matched."""
        n = 0
        with self._lock:
            for e in self._events[since:]:
                if e.get("name") == name and e.get("ph") != "M":
                    e["args"] = {**e.get("args", {}), **extra}
                    n += 1
        return n

    def _tid_locked(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            tid = self._tids[ident] = len(self._tids)
        return tid

    def _meta_process(self, pid: int, label: str) -> None:
        self._events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": label},
            }
        )

    # ------------------------------------------------------- cross-node merge

    def serialize(self) -> list[dict]:
        """The span/event list for the wire (metadata rows excluded — the
        ingesting side assigns its own pid + process_name)."""
        with self._lock:
            return [dict(e) for e in self._events if e.get("ph") != "M"]

    def ingest(
        self, events: list[dict], offset_s: float = 0.0, process: str = "remote"
    ) -> int:
        """Merge a remote tracer's serialized records, shifting their
        wall-clock timestamps by ``-offset_s`` into this tracer's clock
        (``offset_s`` = remote_clock - local_clock at a common instant).
        Each distinct ``process`` label gets its own Chrome pid.  Returns
        records merged; malformed entries are skipped, never raised on
        (telemetry must not take down a job)."""
        n = 0
        with self._lock:
            pid = self._pids.get(process)
            if pid is None:
                pid = self._pids[process] = max(self._pids.values()) + 1
                self._meta_process(pid, process)
            for e in events:
                if not isinstance(e, dict) or e.get("ph") not in ("X", "i"):
                    continue
                try:
                    ts = float(e["ts"]) - offset_s * 1e6
                except (KeyError, TypeError, ValueError):
                    continue
                merged = dict(e, pid=pid, ts=round(ts, 1))
                self._events.append(merged)
                n += 1
        return n

    # --------------------------------------------------------------- export

    def counts(self) -> dict:
        with self._lock:
            spans = sum(1 for e in self._events if e.get("ph") == "X")
            events = sum(1 for e in self._events if e.get("ph") == "i")
        return {"spans": spans, "events": events}

    def to_chrome(self, metrics: dict | None = None) -> dict:
        """The Chrome-trace JSON object (loadable in chrome://tracing and
        ui.perfetto.dev)."""
        with self._lock:
            events = [dict(e) for e in self._events]
        other = {"trace_id": self.trace_id, "clock": "epoch_us"}
        if metrics is not None:
            other["metrics"] = metrics
        return {"traceEvents": events, "otherData": other}

    def export(self, path: str, metrics: dict | None = None) -> dict:
        doc = self.to_chrome(metrics)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return doc
