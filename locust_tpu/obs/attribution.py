"""Automatic device-time attribution: xplane family times joined onto
stage spans.

VERDICT r5 "Next" #2/#3 (a MEASURED ``profiled_roofline`` capture and
the ``phase_stage_device_time`` stage parity) were blocked on plumbing:
the profiler capture (utils/profiling.profile_device), the family
reduction (parse_xplane sort/scatter/dot totals) and the stage spans
lived in three places nobody joined.  This module is the join:

  * ``family_join`` — the ONE copy of the Process-family pairing rule
    (sort modes pair with the sort HLO family; the hasht family adds
    scatters; hasht-mxu adds the one-hot dots — pairing one-hot bytes
    with a dot-free time would inflate utilization past honesty);
    scripts/opp_resume.phase_profile and this module both use it, so the
    sweep's utilization math and the trace annotations cannot drift;
  * ``attributed_run`` — run a callable under ``profile_device`` and, if
    a tracer is active, annotate its ``engine.stage.process`` spans with
    the measured device families (an ``obs.device_join`` instant marks
    the join in the timeline);
  * ``record_stage_device_row`` — the evidence row (ledger kind
    ``stage_device_time``, ``source="obs_attribution"``) the profiled
    sweep phase now emits alongside ``profiled_roofline`` with no extra
    phases: TPU rows land opportunistically in a tunnel window, CPU
    fallback rows land with ``backend: "cpu"`` (every TPU-evidence
    reader filters on backend, so CPU rows can never masquerade).

Caveat (docs/OBSERVABILITY.md): one xplane capture has no per-stage op
correlation, so the families attribute to the PROCESS stage — the stage
whose op families they are by construction (profiling.SORT/SCATTER/
DOT_OP_FRAGMENTS); map/reduce elementwise work hides in fusions and is
deliberately not claimed.
"""

from __future__ import annotations

from locust_tpu import obs
from locust_tpu.utils import profiling

# The stage span the device families attach to (see module docstring).
PROCESS_STAGE_SPAN = "engine.stage.process"


def family_join(summary: dict, sort_mode: str) -> dict:
    """Pair a parsed xplane ``summary`` with ``sort_mode``'s Process-stage
    op families.  Returns the joined fields (all floats may be None when
    the capture carried no device plane)."""
    if summary.get("error"):
        return {"error": summary["error"]}
    from locust_tpu.config import HASHT_FAMILY

    sort_ms = summary.get("sort_ms")
    scatter_ms = summary.get("scatter_ms")
    dot_ms = summary.get("dot_ms")
    kernel_ms = summary.get("kernel_ms")
    family = "sort"
    process_ms = sort_ms
    if sort_mode in HASHT_FAMILY:
        process_ms = (scatter_ms or 0.0) + (sort_ms or 0.0)
        family = "scatter+sort"
        if sort_mode == "hasht-mxu":
            process_ms += dot_ms or 0.0
            family = "scatter+sort+dot"
        elif sort_mode == "fused":
            # The megakernel's device time is ONE custom call
            # (profiling.FUSED_KERNEL_OP_FRAGMENTS) the scatter/sort
            # families never see; the mode's traffic model includes the
            # kernel's bytes (roofline est_kernel_bytes), so its time
            # must pair in too — the hasht-mxu dot-family rule again.
            process_ms += kernel_ms or 0.0
            family = "scatter+sort+kernel"
    return {
        "process_family": family,
        "process_device_ms": (
            round(process_ms, 3) if process_ms is not None else None
        ),
        "sort_device_ms": sort_ms,
        "scatter_device_ms": scatter_ms,
        "dot_device_ms": dot_ms,
        "kernel_device_ms": kernel_ms,
        "device_total_ms": summary.get("device_total_ms"),
        "device_plane": summary.get("device_plane"),
    }


def attributed_run(fn, out_dir: str, sort_mode: str):
    """Run ``fn()`` under a profiler capture and join the parsed device
    families onto the active tracer's Process-stage spans.

    Returns ``(fn_result, summary, xplane_path, join)`` — the first three
    exactly as ``profiling.profile_device`` (evidence collection never
    raises), ``join`` from ``family_join``.  The annotation is a no-op
    when telemetry is disabled or the run emitted no stage spans (e.g. a
    fused ``run_blocks`` capture) — the join dict still carries the
    numbers for the evidence rows either way.
    """
    tracer = obs.current()
    mark = tracer.event_count() if tracer is not None else 0
    result, summary, xplane = profiling.profile_device(fn, out_dir)
    join = family_join(summary, sort_mode)
    if tracer is not None and "error" not in join:
        # Annotate only the spans THIS capture ran (since=mark): a
        # warm-up timed_run earlier in the session must not inherit
        # device times the profiler never measured for it.
        matched = tracer.annotate(PROCESS_STAGE_SPAN, join, since=mark)
        obs.event(
            "obs.device_join",
            stage=PROCESS_STAGE_SPAN,
            spans_annotated=matched,
            process_family=join["process_family"],
            process_device_ms=join["process_device_ms"],
        )
    return result, summary, xplane, join


def record_stage_device_row(
    join: dict, meta: dict, times=None, force: bool = False
) -> dict:
    """Append the attribution evidence row (kind ``stage_device_time``,
    the ``phase_stage_device_time`` deliverable's ledger kind).

    ``times`` (an ``engine.StageTimes``) adds the wall-clock stage split
    when the captured run was a ``timed_run``; ``force=True`` writes the
    row off-TPU too (CPU-fallback evidence, ``backend`` field says so).
    """
    from locust_tpu.utils import artifacts

    row = {**meta, **join, "source": "obs_attribution"}
    if times is not None:
        row.update(
            map_wall_ms=round(times.map_ms, 3),
            process_wall_ms=round(times.process_ms, 3),
            reduce_wall_ms=round(times.reduce_ms, 3),
        )
    artifacts.record("stage_device_time", row, force=force)
    return row
