"""Trace-document validation against the checked-in JSON schema.

``locust_tpu/obs/trace.schema.json`` is the contract every exported
timeline must satisfy (tests, scripts/check.py's round-trip, and any
external consumer pointing a real JSON-Schema validator at it).  It
ships INSIDE the package (pyproject package-data) so an installed wheel
validates the same as a repo checkout.  The container ships no
``jsonschema`` package, so ``validate_trace`` implements the small
declarative subset the schema uses — type / required / properties /
items / enum — plus the one conditional JSON Schema would need ``if``/
``then`` for: a complete ("X") event must carry ``ts`` and ``dur``, an
instant ("i") must carry ``ts``.

Failures raise ``ValueError`` listing every violation (a schema gate
that reports one error per run is a gate nobody burns down).
"""

from __future__ import annotations

import json
import os

SCHEMA_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "trace.schema.json"
)

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "number": (int, float),
    "integer": int,
    "boolean": bool,
}


def _check(obj, schema: dict, path: str, errors: list[str]) -> None:
    t = schema.get("type")
    if t is not None:
        py = _TYPES.get(t)
        ok = isinstance(obj, py) if py is not None else True
        if t in ("number", "integer") and isinstance(obj, bool):
            ok = False
        if not ok:
            errors.append(f"{path}: expected {t}, got {type(obj).__name__}")
            return
    if "enum" in schema and obj not in schema["enum"]:
        errors.append(f"{path}: {obj!r} not in {schema['enum']}")
    if isinstance(obj, dict):
        for req in schema.get("required", ()):
            if req not in obj:
                errors.append(f"{path}: missing required key {req!r}")
        for key, sub in schema.get("properties", {}).items():
            if key in obj:
                _check(obj[key], sub, f"{path}.{key}", errors)
    if isinstance(obj, list) and "items" in schema:
        for i, item in enumerate(obj):
            _check(item, schema["items"], f"{path}[{i}]", errors)


def load_schema(path: str | None = None) -> dict:
    with open(path or SCHEMA_PATH, encoding="utf-8") as f:
        return json.load(f)


def validate_trace(doc: dict, schema_path: str | None = None) -> None:
    """Raise ``ValueError`` (all violations listed) unless ``doc`` is a
    valid exported timeline."""
    errors: list[str] = []
    _check(doc, load_schema(schema_path), "$", errors)
    if isinstance(doc, dict):
        for i, e in enumerate(doc.get("traceEvents") or ()):
            if not isinstance(e, dict):
                continue
            ph = e.get("ph")
            if ph == "X" and not ("ts" in e and "dur" in e):
                errors.append(
                    f"$.traceEvents[{i}]: complete event needs ts and dur"
                )
            elif ph == "i" and "ts" not in e:
                errors.append(f"$.traceEvents[{i}]: instant event needs ts")
    if errors:
        raise ValueError(
            "trace document fails obs/trace.schema.json:\n  "
            + "\n  ".join(errors[:20])
            + ("" if len(errors) <= 20 else f"\n  ... {len(errors) - 20} more")
        )
