"""locust_tpu.obs — unified telemetry: tracing, metrics, attribution.

One subsystem replaces the fragmented observability that had accreted
across the repo (SpanTimer wall spans, xplane parsing, per-shard stats,
stream stall accounting): a process-wide ``Tracer`` with nested named
spans + instant events, a closed-registry ``Metrics`` surface, Chrome-
trace/Perfetto export, cross-node span merging over the distributor
wire, and xplane device-time attribution (``obs.attribution``).  See
docs/OBSERVABILITY.md; the name registry is ``obs/names.py`` (analysis
rule R009 keeps it honest in both directions).

ZERO-overhead disabled contract (same stance as ``utils.faultplan``):
telemetry is OFF by default, and every module hook below bails before
allocating anything — ``span()`` returns one shared null context
manager, ``event``/``metric_*`` return after a thread-local peek + one
global load.  Enable with ``obs.enable()`` (CLI: ``--trace-out FILE``;
API: ``EngineConfig(trace=True)``); the engine/distributor call sites
stay in the code permanently and cost nothing when disabled — pinned by
tests/test_obs.py's overhead guard.

Scoping: ``scoped(tracer)`` pushes a thread-local override (``None``
masks the global tracer) — how a worker daemon serving a traced map
request records into a request-scoped tracer without cross-talk from,
or double-counting into, a tracer enabled in the same process (loopback
clusters share one process).  jax-free at import: safe before backend
selection, safe in jax-free supervisors.
"""

from __future__ import annotations

import contextlib
import threading

from locust_tpu.obs.metrics import Metrics
from locust_tpu.obs.names import NAMES  # noqa: F401 - public registry
from locust_tpu.obs.trace import NULL_SPAN, Tracer

_TRACER: Tracer | None = None
_METRICS: Metrics | None = None
_TLS = threading.local()


def enable(process: str = "main", trace_id: str | None = None) -> Tracer:
    """Turn the process tracer + metrics on (idempotent: an existing
    tracer is kept so nested enables share one timeline)."""
    global _TRACER, _METRICS
    if _TRACER is None:
        _TRACER = Tracer(trace_id=trace_id, process=process)
        _METRICS = Metrics()
    return _TRACER


def disable() -> None:
    global _TRACER, _METRICS
    _TRACER = None
    _METRICS = None


def current() -> Tracer | None:
    """The tracer this thread records into: the innermost ``scoped``
    override if any (``None`` = masked off), else the process tracer."""
    stack = getattr(_TLS, "stack", None)
    if stack:
        return stack[-1]
    return _TRACER


@contextlib.contextmanager
def scoped(tracer: Tracer | None):
    """Thread-local tracer override for the block (None masks telemetry
    entirely — a worker handling an untraced request must not leak its
    spans into a tracer enabled in the same loopback process)."""
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(tracer)
    try:
        yield tracer
    finally:
        stack.pop()


# ------------------------------------------------------------- emit hooks
#
# Call sites stay one line and permanently in the code; each hook's first
# statements bail on "disabled" before allocating.


def span(name: str, *sync_refs, **args):
    t = current()
    if t is None:
        return NULL_SPAN
    return t.span(name, *sync_refs, **args)


def event(name: str, **args) -> None:
    t = current()
    if t is None:
        return
    t.event(name, **args)


def _metrics_here() -> Metrics | None:
    """Metrics are PROCESS-scoped (one snapshot per exported timeline),
    so they record only for threads whose current tracer IS the process
    tracer: a ``scoped(None)`` mask suppresses them like spans, and a
    request-scoped tracer (a worker serving someone else's traced map in
    a shared loopback process) must not count its work into this
    process's totals.  Globals are read ONCE into locals — a concurrent
    ``disable()`` (e.g. the master's exit path with abandoned fetch
    threads still draining) must make hooks no-ops, never AttributeError.
    """
    m, t = _METRICS, _TRACER
    if m is None or current() is not t:
        return None
    return m


def metric_inc(name: str, n: float = 1) -> None:
    m = _metrics_here()
    if m is not None:
        m.inc(name, n)


def metric_set(name: str, value: float) -> None:
    m = _metrics_here()
    if m is not None:
        m.set(name, value)


def metric_observe(name: str, value: float) -> None:
    m = _metrics_here()
    if m is not None:
        m.observe(name, value)


# ----------------------------------------------------------------- readout


def metrics_snapshot() -> dict:
    return _METRICS.snapshot() if _METRICS is not None else {}


def summary() -> dict:
    """Compact enabled-state readout (bench's ``obs`` sub-dict)."""
    if _TRACER is None:
        return {"enabled": False}
    return {
        "enabled": True,
        "trace_id": _TRACER.trace_id,
        **_TRACER.counts(),
        "metrics": metrics_snapshot(),
    }


def export(path: str) -> dict | None:
    """Write the process tracer's merged timeline (+ metrics snapshot)
    as Chrome-trace JSON; returns the document, or None when disabled."""
    if _TRACER is None:
        return None
    return _TRACER.export(path, metrics=metrics_snapshot())
