"""Closed-registry metrics: counters, gauges and histograms.

The registry is ``locust_tpu.obs.names`` (one dict for spans, events AND
metrics); an unregistered or kind-mismatched name raises on the enabled
path, and analysis rule R009 pins the same contract statically.  The
histogram keeps streaming moments (count/sum/min/max) — enough for the
bench's ``obs`` sub-dict without bucket configuration.

Thread-safe under one lock (stream folds, the async checkpoint writer
and distributor fetch threads all emit concurrently); the zero-overhead
disabled path lives in ``locust_tpu.obs.__init__``.
"""

from __future__ import annotations

import threading

from locust_tpu.obs import names as _names


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, dict] = {}

    def inc(self, name: str, n: float = 1) -> None:
        _names.check(name, "counter")
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set(self, name: str, value: float) -> None:
        _names.check(name, "gauge")
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        _names.check(name, "histogram")
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = {
                    "count": 0, "sum": 0.0, "min": value, "max": value,
                }
            h["count"] += 1
            h["sum"] += value
            h["min"] = min(h["min"], value)
            h["max"] = max(h["max"], value)

    def snapshot(self) -> dict:
        """One JSON-able view (bench ``obs`` sub-dict, trace otherData)."""
        with self._lock:
            hists = {
                k: dict(
                    h,
                    sum=round(h["sum"], 3),
                    min=round(h["min"], 3),
                    max=round(h["max"], 3),
                    mean=round(h["sum"] / h["count"], 3) if h["count"] else 0.0,
                )
                for k, h in self._hists.items()
            }
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": hists,
            }
