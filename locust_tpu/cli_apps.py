"""CLI subcommands for the workload ladder beyond WordCount.

The reference's entire capability is CLI-driven (reference
MapReduce/src/main.cu:358-387, README.md:12-24); ours matched that for
WordCount but left PageRank / inverted index / TF-IDF library-only
(VERDICT r3 missing #5).  These subcommands wire the existing apps:

  python -m locust_tpu pagerank <edges.txt> [--mesh] [--num-iters N]
  python -m locust_tpu index  <file> [--mesh] [--lines-per-doc K]
  python -m locust_tpu tfidf  <file> [--lines-per-doc K]

Edge-list format: one ``src dst`` pair of integer node ids per line;
lines starting with ``#`` are comments (the web-Google / SNAP convention,
BASELINE.json configs[3]).  For index/tfidf the doc id of line i is
``i // lines_per_doc`` — line-sharded documents, the same convention as
the library tests.

``--mesh`` selects the sharded engines (ShardedPageRank — rank state
O(nodes/n_dev) per device — and DistributedInvertedIndex) over all
visible devices; without it the single-device variants run.  Backend
resolution (probe/fallback) is shared with the WordCount path.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

SUBCOMMANDS = ("pagerank", "index", "tfidf")


def _add_backend_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--backend", choices=["auto", "cpu", "tpu"], default="auto",
        help="auto: accelerator if its init probe passes, else CPU",
    )


def build_parser(cmd: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=f"locust_tpu {cmd}")
    if cmd == "pagerank":
        p.add_argument("edges", help="edge list: 'src dst' per line, # comments")
        p.add_argument("--num-iters", type=int, default=20)
        p.add_argument("--damping", type=float, default=0.85)
        p.add_argument("--num-nodes", type=int, default=None,
                       help="default: max node id in the file + 1")
        p.add_argument("--mesh", action="store_true",
                       help="ShardedPageRank over all visible devices "
                            "(rank state sharded O(nodes/n_dev))")
        p.add_argument("--top", type=int, default=None,
                       help="print only the N highest-ranked nodes")
    else:
        p.add_argument("filename", help="input text file")
        p.add_argument("--lines-per-doc", type=int, default=1,
                       help="doc id of line i = i // K (default 1: "
                            "one document per line)")
        p.add_argument("--mesh", action="store_true",
                       help="build across all visible devices "
                            "(DistributedInvertedIndex shuffle)")
        p.add_argument("--limit", type=int, default=None,
                       help="print only the first N table rows")
        p.add_argument("--block-lines", type=int, default=4096)
        p.add_argument("--line-width", type=int, default=128)
        p.add_argument("--key-width", type=int, default=32)
        p.add_argument("--emits-per-line", type=int, default=20)
    _add_backend_flag(p)
    return p


def load_edges(path: str) -> tuple[np.ndarray, np.ndarray]:
    """Parse a SNAP-style edge list; loud error on malformed lines."""
    src, dst = [], []
    with open(path, "rb") as f:
        for ln_no, ln in enumerate(f, 1):
            ln = ln.strip()
            if not ln or ln.startswith(b"#"):
                continue
            parts = ln.split()
            if len(parts) != 2:
                raise ValueError(
                    f"{path}:{ln_no}: expected 'src dst', got {ln[:60]!r}"
                )
            src.append(int(parts[0]))
            dst.append(int(parts[1]))
    if not src:
        raise ValueError(f"{path}: no edges")
    s = np.asarray(src, np.int64)
    d = np.asarray(dst, np.int64)
    if s.min() < 0 or d.min() < 0:
        raise ValueError(f"{path}: negative node id")
    return s, d




def run_pagerank(args) -> int:
    src, dst = load_edges(args.edges)
    n = (
        args.num_nodes
        if args.num_nodes is not None
        else int(max(src.max(), dst.max())) + 1
    )
    if max(int(src.max()), int(dst.max())) >= n:
        print(
            f"locust_tpu: error: --num-nodes {n} but max node id is "
            f"{max(int(src.max()), int(dst.max()))}",
            file=sys.stderr,
        )
        return 1
    if args.mesh:
        from locust_tpu.apps.pagerank import ShardedPageRank
        from locust_tpu.parallel.mesh import make_mesh

        ranks = ShardedPageRank(make_mesh(), n, damping=args.damping).run(
            src, dst, num_iters=args.num_iters
        )
    else:
        from locust_tpu.apps.pagerank import pagerank

        ranks = np.asarray(
            pagerank(
                np.asarray(src, np.int32),
                np.asarray(dst, np.int32),
                num_nodes=n,
                num_iters=args.num_iters,
                damping=args.damping,
            )
        )
    order = (
        np.argsort(-ranks, kind="stable")[: args.top]
        if args.top is not None
        else np.arange(n)
    )
    out = sys.stdout
    for node in order:
        out.write(f"{node}\t{ranks[node]:.8f}\n")
    out.flush()
    return 0


def _load_docs(args):
    import jax

    from locust_tpu.config import EngineConfig, default_sort_mode
    from locust_tpu.io import loader

    cfg = EngineConfig(
        block_lines=args.block_lines,
        line_width=args.line_width,
        key_width=args.key_width,
        emits_per_line=args.emits_per_line,
        # Measured per-backend Process default (backend already selected
        # by main's select_backend_cli); apps inherit the same fold wins.
        sort_mode=default_sort_mode(jax.default_backend()),
    )
    rows = loader.load_rows(args.filename, cfg.line_width)
    ids = (np.arange(rows.shape[0]) // args.lines_per_doc).astype(np.int32)
    return cfg, rows, ids


def run_index(args) -> int:
    cfg, rows, ids = _load_docs(args)
    if args.mesh:
        from locust_tpu.apps.inverted_index import build_inverted_index_mesh
        from locust_tpu.parallel.mesh import make_mesh

        index = build_inverted_index_mesh(rows, ids, make_mesh(), cfg)
    else:
        from locust_tpu.apps.inverted_index import build_inverted_index

        index = build_inverted_index(rows, ids, cfg)
    out = sys.stdout.buffer
    for i, word in enumerate(sorted(index)):
        if args.limit is not None and i >= args.limit:
            break
        docs = b",".join(str(d).encode() for d in index[word])
        out.write(word + b"\t" + docs + b"\n")
    out.flush()
    return 0


def run_tfidf(args) -> int:
    cfg, rows, ids = _load_docs(args)
    from locust_tpu.apps.tfidf import build_tfidf

    scores = build_tfidf(rows, ids, cfg)
    out = sys.stdout.buffer
    for i, (word, doc) in enumerate(sorted(scores)):
        if args.limit is not None and i >= args.limit:
            break
        out.write(
            word + b"\t" + str(doc).encode()
            + b"\t" + f"{scores[(word, doc)]:.6f}".encode() + b"\n"
        )
    out.flush()
    return 0


def main(cmd: str, argv) -> int:
    args = build_parser(cmd).parse_args(argv)
    # Pure argument validation BEFORE backend resolution: a trivially
    # invalid invocation must not burn ~3 minutes of TPU probe/retry
    # against a flapping tunnel before its error prints.
    if cmd == "tfidf" and args.mesh:
        print(
            "locust_tpu: error: tfidf has no mesh variant (the tf pair "
            "table is device-bounded; use index --mesh for the "
            "distributed path)",
            file=sys.stderr,
        )
        return 2
    if cmd != "pagerank" and args.lines_per_doc < 1:
        print("locust_tpu: error: --lines-per-doc must be >= 1",
              file=sys.stderr)
        return 2
    if cmd == "pagerank":
        if args.num_nodes is not None and args.num_nodes < 1:
            print("locust_tpu: error: --num-nodes must be >= 1",
                  file=sys.stderr)
            return 2
        if args.top is not None and args.top < 1:
            print("locust_tpu: error: --top must be >= 1", file=sys.stderr)
            return 2
    from locust_tpu.backend import select_backend_cli

    if select_backend_cli(args.backend) is None:
        return 1
    try:
        if cmd == "pagerank":
            return run_pagerank(args)
        if cmd == "index":
            return run_index(args)
        return run_tfidf(args)
    except (OSError, ValueError) as e:
        print(f"locust_tpu: error: {e}", file=sys.stderr)
        return 1
