"""CLI subcommands for the workload ladder beyond WordCount.

The reference's entire capability is CLI-driven (reference
MapReduce/src/main.cu:358-387, README.md:12-24); ours matched that for
WordCount but left PageRank / inverted index / TF-IDF library-only
(VERDICT r3 missing #5).  Since the plan layer (docs/PLAN.md) these
drivers no longer hand-wire stage chains: each one CONSTRUCTS the
workload's canonical logical plan (locust_tpu/plan/builders.py) and runs
it through the plan compiler, which lowers onto the same apps/engine
primitives — output byte-identical to the pre-plan drivers (pinned by
tests/test_plan.py).  These subcommands wire the existing apps:

  python -m locust_tpu pagerank <edges.txt> [--mesh] [--num-iters N]
  python -m locust_tpu index  <file> [--mesh] [--lines-per-doc K]
  python -m locust_tpu tfidf  <file> [--lines-per-doc K]

Edge-list format: one ``src dst`` pair of integer node ids per line;
lines starting with ``#`` are comments (the web-Google / SNAP convention,
BASELINE.json configs[3]).  For index/tfidf the doc id of line i is
``i // lines_per_doc`` — line-sharded documents, the same convention as
the library tests.

``--mesh`` selects the sharded engines (ShardedPageRank — rank state
O(nodes/n_dev) per device — and DistributedInvertedIndex) over all
visible devices; without it the single-device variants run.  Backend
resolution (probe/fallback) is shared with the WordCount path.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from locust_tpu import obs  # jax-free; zero-overhead unless --trace-out

SUBCOMMANDS = ("pagerank", "index", "tfidf")


def _add_backend_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--backend", choices=["auto", "cpu", "tpu"], default="auto",
        help="auto: accelerator if its init probe passes, else CPU",
    )
    # Ladder/WordCount CLI parity: every subcommand takes the main CLI's
    # observability + sort-strategy flags, so a plan-compiled ladder run
    # is traceable and tunable with zero new plumbing.
    from locust_tpu.config import SORT_MODES

    p.add_argument(
        "--sort-mode", choices=list(SORT_MODES), default=None,
        help="Process-stage sort strategy (config.EngineConfig."
             "sort_mode); default follows the measured per-backend "
             "choice (config.default_sort_mode).  pagerank accepts it "
             "for ladder parity only — its dense pipeline has no sort.",
    )
    p.add_argument(
        "--trace-out", default=None, metavar="FILE",
        help="structured telemetry (locust_tpu.obs): record the run's "
             "spans/events/metrics (plan.compile/plan.run + engine "
             "stages) and export a Chrome-trace JSON timeline to FILE "
             "(docs/OBSERVABILITY.md)",
    )


def build_parser(cmd: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=f"locust_tpu {cmd}")
    if cmd == "pagerank":
        p.add_argument("edges", help="edge list: 'src dst' per line, # comments")
        p.add_argument("--num-iters", type=int, default=20)
        p.add_argument("--damping", type=float, default=0.85)
        p.add_argument("--num-nodes", type=int, default=None,
                       help="default: max node id in the file + 1")
        p.add_argument("--mesh", action="store_true",
                       help="ShardedPageRank over all visible devices "
                            "(rank state sharded O(nodes/n_dev))")
        p.add_argument("--top", type=int, default=None,
                       help="print only the N highest-ranked nodes")
    else:
        p.add_argument("filename", help="input text file")
        p.add_argument("--lines-per-doc", type=int, default=1,
                       help="doc id of line i = i // K (default 1: "
                            "one document per line)")
        p.add_argument("--mesh", action="store_true",
                       help="build across all visible devices "
                            "(DistributedInvertedIndex shuffle)")
        p.add_argument("--limit", type=int, default=None,
                       help="print only the first N table rows")
        p.add_argument("--block-lines", type=int, default=4096)
        p.add_argument("--line-width", type=int, default=128)
        p.add_argument("--key-width", type=int, default=32)
        p.add_argument("--emits-per-line", type=int, default=20)
    _add_backend_flag(p)
    return p


def load_edges(path: str) -> tuple[np.ndarray, np.ndarray]:
    """Parse a SNAP-style edge list; loud error on malformed lines.

    Delegates to the ONE byte-level parser
    (``plan.compile.edges_from_bytes``) so the CLI and a pagerank plan
    submitted to the serve daemon can never disagree about the format;
    the file path is prefixed onto any parse error for CLI context."""
    from locust_tpu.plan import PlanError
    from locust_tpu.plan.compile import edges_from_bytes

    with open(path, "rb") as f:
        data = f.read()
    try:
        return edges_from_bytes(data)
    except PlanError as e:
        raise ValueError(f"{path}: {e}")




def run_pagerank(args) -> int:
    src, dst = load_edges(args.edges)
    n = (
        args.num_nodes
        if args.num_nodes is not None
        else int(max(src.max(), dst.max())) + 1
    )
    if max(int(src.max()), int(dst.max())) >= n:
        print(
            f"locust_tpu: error: --num-nodes {n} but max node id is "
            f"{max(int(src.max()), int(dst.max()))}",
            file=sys.stderr,
        )
        return 1
    from locust_tpu.plan import pagerank_plan
    from locust_tpu.plan.compile import compile_plan

    # The driver constructs the canonical plan and lets the compiler
    # pick the lowering (apps.pagerank single-device vs ShardedPageRank
    # under --mesh) — same value, byte-identical output (docs/PLAN.md).
    ranks = compile_plan(
        pagerank_plan(num_iters=args.num_iters, damping=args.damping),
        mesh=args.mesh,
    ).run((src, dst), num_nodes=n, render=False).value
    from locust_tpu.plan.compile import rank_row

    order = (
        np.argsort(-ranks, kind="stable")[: args.top]
        if args.top is not None
        else np.arange(n)
    )
    out = sys.stdout.buffer
    for node in order:
        out.write(rank_row(int(node), ranks[node]))
    out.flush()
    return 0


def _load_docs(args):
    import jax

    from locust_tpu.config import EngineConfig, default_sort_mode
    from locust_tpu.io import loader

    cfg = EngineConfig(
        block_lines=args.block_lines,
        line_width=args.line_width,
        key_width=args.key_width,
        emits_per_line=args.emits_per_line,
        # Measured per-backend Process default (backend already selected
        # by main's select_backend_cli); apps inherit the same fold wins.
        # --sort-mode overrides it, same as the WordCount CLI.
        sort_mode=args.sort_mode or default_sort_mode(jax.default_backend()),
    )
    rows = loader.load_rows(args.filename, cfg.line_width)
    return cfg, rows


def run_index(args) -> int:
    cfg, rows = _load_docs(args)
    from locust_tpu.plan import index_plan
    from locust_tpu.plan.compile import compile_plan

    # Plan-compiled: the source node derives the line->doc sharding
    # (``i // lines_per_doc``, the module contract above) and the
    # compiler lowers onto build_inverted_index[_mesh].
    index = compile_plan(
        index_plan(args.lines_per_doc), cfg, mesh=args.mesh
    ).run(rows, render=False).value
    return _print_rendered("postings", index, args.limit)


def _print_rendered(op: str, value, limit) -> int:
    """Print through the plan sink's ONE row renderer
    (plan.compile.iter_rendered) — the driver's stdout and a plan
    job's rendered result stay byte-identical by construction."""
    from locust_tpu.plan.compile import iter_rendered

    out = sys.stdout.buffer
    for i, row in enumerate(iter_rendered(op, value)):
        if limit is not None and i >= limit:
            break
        out.write(row)
    out.flush()
    return 0


def run_tfidf(args) -> int:
    cfg, rows = _load_docs(args)
    from locust_tpu.plan import tfidf_plan
    from locust_tpu.plan.compile import compile_plan

    scores = compile_plan(
        tfidf_plan(args.lines_per_doc), cfg
    ).run(rows, render=False).value
    return _print_rendered("tfidf", scores, args.limit)


def main(cmd: str, argv) -> int:
    args = build_parser(cmd).parse_args(argv)
    # Pure argument validation BEFORE backend resolution: a trivially
    # invalid invocation must not burn ~3 minutes of TPU probe/retry
    # against a flapping tunnel before its error prints.
    if cmd == "tfidf" and args.mesh:
        print(
            "locust_tpu: error: tfidf has no mesh variant (the tf pair "
            "table is device-bounded; use index --mesh for the "
            "distributed path)",
            file=sys.stderr,
        )
        return 2
    if cmd != "pagerank" and args.lines_per_doc < 1:
        print("locust_tpu: error: --lines-per-doc must be >= 1",
              file=sys.stderr)
        return 2
    if cmd == "pagerank":
        if args.num_nodes is not None and args.num_nodes < 1:
            print("locust_tpu: error: --num-nodes must be >= 1",
                  file=sys.stderr)
            return 2
        if args.top is not None and args.top < 1:
            print("locust_tpu: error: --top must be >= 1", file=sys.stderr)
            return 2
    from locust_tpu.backend import select_backend_cli

    if select_backend_cli(args.backend) is None:
        return 1
    if args.trace_out:
        obs.enable(process="cli")
    try:
        if cmd == "pagerank":
            return run_pagerank(args)
        if cmd == "index":
            return run_index(args)
        return run_tfidf(args)
    except (OSError, ValueError) as e:
        print(f"locust_tpu: error: {e}", file=sys.stderr)
        return 1
    finally:
        if args.trace_out:
            # Same stance as the WordCount CLI: telemetry must not take
            # down (or re-color) the run — an unwritable trace path is a
            # warning, never the exit status.
            try:
                obs.export(args.trace_out)
                print(f"[locust] trace written to {args.trace_out}",
                      file=sys.stderr)
            except OSError as e:
                print(f"[locust] trace export to {args.trace_out} "
                      f"failed: {e}", file=sys.stderr)
            obs.disable()
