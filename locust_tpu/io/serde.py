"""Intermediate-result serde: the inter-stage / inter-process data plane.

The reference's only inter-process format is a ``key\\tvalue`` TSV at
``/tmp/out.txt`` written by the map stage (``writeKeyIntValues``, reference
MapReduce/src/main.cu:116-124) and re-read by the reduce stage
(``loadIntermediateFile``, main.cu:66-103).  That file is also its entire
checkpoint/resume story (SURVEY.md §5).

Kept for CLI/staged-mode parity, with fixes:
  Q5  — the reference writes a trailing space in every key (``"%s \\t%d"``,
        main.cu:121); we write clean ``key\\tvalue`` but *accept* trailing
        spaces on read for compatibility with reference-produced files.
  Q10 — the reference dumps the full uncompacted MAX_EMITS buffer; we write
        only live entries.

For TPU-shard checkpoints (stage-level resume at scale) the binary ``npz``
format stores the packed device representation directly.

The distributor's data plane (docs/DATAPLANE.md) stages intermediates in
the packed binary KV format below instead of TSV: columnar (lens blob /
key blob / values array) so the master decodes straight into padded key
rows + an int32 vector with ``np.frombuffer`` — no per-line text parse —
and so the post-combine stream compresses well on the wire (sorted keys,
shared prefixes).  ``read_intermediate`` sniffs the magic, so mixed
TSV/binary inputs (old workers, reference-produced files) reduce fine.
"""

from __future__ import annotations

import struct

import numpy as np

from locust_tpu.core import bytes_ops
from locust_tpu.core.kv import KVBatch

# Packed binary KV intermediate ("LKVB" v1).  Layout, all little-endian:
#   0   4  magic b"LKVB"
#   4   1  version (1)
#   5   1  flags (0)
#   6   2  reserved (0)
#   8   4  count (u32)
#  12   4  key-blob length (u32)
#  16      u16[count] key lengths
#          key blob (concatenated raw key bytes)
#          i32[count] values
KVB_MAGIC = b"LKVB"
KVB_VERSION = 1
_KVB_HEADER = struct.Struct("<4sBBHII")

INTERMEDIATE_FORMATS = ("tsv", "bin")


def write_tsv(pairs: list[tuple[bytes, int]], path: str) -> None:
    """Write live (key, value) pairs as ``key\\tvalue`` lines."""
    with open(path, "wb") as f:
        for k, v in pairs:
            f.write(k + b"\t" + str(int(v)).encode() + b"\n")


def write_kvbin(pairs: list[tuple[bytes, int]], path: str) -> None:
    """Write live (key, value) pairs in the packed binary KV format."""
    for k, _ in pairs:
        if len(k) > 0xFFFF:
            raise ValueError(
                f"key of {len(k)} bytes exceeds the u16 length field"
            )
    lens = np.fromiter((len(k) for k, _ in pairs), np.uint16, len(pairs))
    values = np.fromiter((int(v) for _, v in pairs), np.int64, len(pairs))
    if len(values) and not (
        values.min() >= -(2**31) and values.max() < 2**31
    ):
        raise OverflowError(f"value outside int32 in {path!r}")
    blob = b"".join(k for k, _ in pairs)
    with open(path, "wb") as f:
        f.write(
            _KVB_HEADER.pack(KVB_MAGIC, KVB_VERSION, 0, 0, len(pairs), len(blob))
        )
        f.write(lens.astype("<u2").tobytes())
        f.write(blob)
        f.write(values.astype("<i4").tobytes())


def read_kvbin(path: str, key_width: int) -> tuple[np.ndarray, np.ndarray]:
    """Packed binary KV -> (padded key rows, int32 values).

    Same output contract as ``read_tsv`` (keys truncated to ``key_width``,
    NUL-padded uint8 rows) so the reduce stage is format-blind.  Any
    structural inconsistency raises ValueError — a truncated or corrupted
    file must never silently yield fewer/garbled pairs (the distributor
    additionally sha256-verifies end to end before this runs).
    """
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < _KVB_HEADER.size:
        raise ValueError(f"{path!r}: truncated KVB header")
    magic, version, _flags, _resv, count, blob_len = _KVB_HEADER.unpack(
        data[: _KVB_HEADER.size]
    )
    if magic != KVB_MAGIC:
        raise ValueError(f"{path!r}: bad KVB magic {magic!r}")
    if version != KVB_VERSION:
        raise ValueError(f"{path!r}: unsupported KVB version {version}")
    want = _KVB_HEADER.size + 2 * count + blob_len + 4 * count
    if len(data) != want:
        raise ValueError(
            f"{path!r}: KVB size mismatch (have {len(data)}B, header "
            f"implies {want}B)"
        )
    off = _KVB_HEADER.size
    lens = np.frombuffer(data, "<u2", count, off).astype(np.int64)
    off += 2 * count
    if int(lens.sum()) != blob_len:
        raise ValueError(f"{path!r}: KVB key lengths do not sum to the blob")
    blob = np.frombuffer(data, np.uint8, blob_len, off)
    off += blob_len
    values = np.frombuffer(data, "<i4", count, off).astype(np.int32)
    rows = np.zeros((count, key_width), np.uint8)
    if count:
        # Vectorized scatter: byte i of the blob lands at (its key's row,
        # its offset within the key), dropped when past key_width.
        starts = np.concatenate(([0], np.cumsum(lens)[:-1]))
        row_of = np.repeat(np.arange(count), lens)
        col_of = np.arange(blob_len) - np.repeat(starts, lens)
        keep = col_of < key_width
        rows[row_of[keep], col_of[keep]] = blob[keep]
    return rows, values


def is_kvbin(path: str) -> bool:
    with open(path, "rb") as f:
        return f.read(len(KVB_MAGIC)) == KVB_MAGIC


def write_intermediate(
    pairs: list[tuple[bytes, int]], path: str, fmt: str = "tsv"
) -> None:
    if fmt not in INTERMEDIATE_FORMATS:
        raise ValueError(f"unknown intermediate format {fmt!r}")
    (write_kvbin if fmt == "bin" else write_tsv)(pairs, path)


def read_intermediate(
    path: str, key_width: int, use_native: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    """Format-sniffing read: packed binary KV by magic, else TSV."""
    if is_kvbin(path):
        return read_kvbin(path, key_width)
    return read_tsv(path, key_width, use_native=use_native)


def read_tsv(
    path: str, key_width: int, use_native: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    """Parse ``key\\tvalue`` TSV -> (padded key rows, int32 values).

    Split on the FIRST tab like the reference's parser (main.cu:84-97);
    tolerate reference-style trailing spaces in keys (Q5) and blank lines.
    A native streaming parser (native/ingest.cpp ``ingest_read_tsv``)
    handles multi-GB intermediates; this Python loop is the always-
    available fallback and the semantic reference.
    """
    if use_native and key_width <= 256:
        try:
            from locust_tpu.io import native_ingest

            return native_ingest.read_tsv(path, key_width)
        except (ImportError, OSError):
            pass
    import re

    # The strict value grammar (shared with the native parser): optional
    # ' '/'\t'/'\r' padding, sign, digits — nothing else.  int(b"1_2") or
    # form-feed padding would be accepted by bare int() but are malformed
    # TSV rows; both parsers must agree row-for-row or key/value alignment
    # would depend on which path ran.  Values beyond int32 raise (a wrap
    # would silently corrupt counts); fields > 63 bytes are malformed.
    val_re = re.compile(rb"[ \t\r]*([+-]?[0-9]+)[ \t\r]*\Z")

    keys: list[bytes] = []
    values: list[int] = []
    with open(path, "rb") as f:
        for line in f:
            line = line.rstrip(b"\n").rstrip(b"\r")
            if not line:
                continue
            key, _, val = line.partition(b"\t")
            key = key.rstrip(b" ")  # reference writes "key \t..." (Q5)
            if not key:
                continue
            m = val_re.fullmatch(val) if len(val) <= 63 else None
            if m is None:
                continue  # malformed row: skip, like the reference's atoi-0 rows
            v = int(m.group(1))
            if not (-(2**31) <= v < 2**31):
                raise OverflowError(
                    f"TSV value {v} in {path!r} does not fit int32"
                )
            values.append(v)
            keys.append(key)
    return bytes_ops.strings_to_rows(keys, key_width), np.asarray(
        values, dtype=np.int32
    )


def fingerprint_corpus(rows: np.ndarray, **extra) -> str:
    """Resume-identity string for a checkpointed run over ``rows``.

    Digests the corpus CONTENT, not just its shape — editing the corpus
    without changing the line count must not resume from a stale snapshot
    (round-1 advisor finding).  ``extra`` carries the pipeline identity
    (config repr, combine, mesh, ...); one shared recipe so the engine and
    the distributed runner can never drift apart.
    """
    import hashlib
    import json

    return json.dumps(
        {
            "n_rows": int(rows.shape[0]),
            "digest": hashlib.sha256(
                np.ascontiguousarray(rows).tobytes()
            ).hexdigest(),
            **extra,
        },
        sort_keys=True,
    )


def write_npz(batch: KVBatch, path: str) -> None:
    """Binary shard checkpoint: the packed device representation as-is."""
    np.savez_compressed(
        path,
        key_lanes=np.asarray(batch.key_lanes),
        values=np.asarray(batch.values),
        valid=np.asarray(batch.valid),
    )


def read_npz(path: str) -> KVBatch:
    import jax.numpy as jnp

    with np.load(path) as z:
        return KVBatch(
            key_lanes=jnp.asarray(z["key_lanes"]),
            values=jnp.asarray(z["values"]),
            valid=jnp.asarray(z["valid"]),
        )
