"""Intermediate-result serde: the inter-stage / inter-process data plane.

The reference's only inter-process format is a ``key\\tvalue`` TSV at
``/tmp/out.txt`` written by the map stage (``writeKeyIntValues``, reference
MapReduce/src/main.cu:116-124) and re-read by the reduce stage
(``loadIntermediateFile``, main.cu:66-103).  That file is also its entire
checkpoint/resume story (SURVEY.md §5).

Kept for CLI/staged-mode parity, with fixes:
  Q5  — the reference writes a trailing space in every key (``"%s \\t%d"``,
        main.cu:121); we write clean ``key\\tvalue`` but *accept* trailing
        spaces on read for compatibility with reference-produced files.
  Q10 — the reference dumps the full uncompacted MAX_EMITS buffer; we write
        only live entries.

For TPU-shard checkpoints (stage-level resume at scale) the binary ``npz``
format stores the packed device representation directly.
"""

from __future__ import annotations

import numpy as np

from locust_tpu.core import bytes_ops
from locust_tpu.core.kv import KVBatch


def write_tsv(pairs: list[tuple[bytes, int]], path: str) -> None:
    """Write live (key, value) pairs as ``key\\tvalue`` lines."""
    with open(path, "wb") as f:
        for k, v in pairs:
            f.write(k + b"\t" + str(int(v)).encode() + b"\n")


def read_tsv(
    path: str, key_width: int, use_native: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    """Parse ``key\\tvalue`` TSV -> (padded key rows, int32 values).

    Split on the FIRST tab like the reference's parser (main.cu:84-97);
    tolerate reference-style trailing spaces in keys (Q5) and blank lines.
    A native streaming parser (native/ingest.cpp ``ingest_read_tsv``)
    handles multi-GB intermediates; this Python loop is the always-
    available fallback and the semantic reference.
    """
    if use_native and key_width <= 256:
        try:
            from locust_tpu.io import native_ingest

            return native_ingest.read_tsv(path, key_width)
        except (ImportError, OSError):
            pass
    import re

    # The strict value grammar (shared with the native parser): optional
    # ' '/'\t'/'\r' padding, sign, digits — nothing else.  int(b"1_2") or
    # form-feed padding would be accepted by bare int() but are malformed
    # TSV rows; both parsers must agree row-for-row or key/value alignment
    # would depend on which path ran.  Values beyond int32 raise (a wrap
    # would silently corrupt counts); fields > 63 bytes are malformed.
    val_re = re.compile(rb"[ \t\r]*([+-]?[0-9]+)[ \t\r]*\Z")

    keys: list[bytes] = []
    values: list[int] = []
    with open(path, "rb") as f:
        for line in f:
            line = line.rstrip(b"\n").rstrip(b"\r")
            if not line:
                continue
            key, _, val = line.partition(b"\t")
            key = key.rstrip(b" ")  # reference writes "key \t..." (Q5)
            if not key:
                continue
            m = val_re.fullmatch(val) if len(val) <= 63 else None
            if m is None:
                continue  # malformed row: skip, like the reference's atoi-0 rows
            v = int(m.group(1))
            if not (-(2**31) <= v < 2**31):
                raise OverflowError(
                    f"TSV value {v} in {path!r} does not fit int32"
                )
            values.append(v)
            keys.append(key)
    return bytes_ops.strings_to_rows(keys, key_width), np.asarray(
        values, dtype=np.int32
    )


def fingerprint_corpus(rows: np.ndarray, **extra) -> str:
    """Resume-identity string for a checkpointed run over ``rows``.

    Digests the corpus CONTENT, not just its shape — editing the corpus
    without changing the line count must not resume from a stale snapshot
    (round-1 advisor finding).  ``extra`` carries the pipeline identity
    (config repr, combine, mesh, ...); one shared recipe so the engine and
    the distributed runner can never drift apart.
    """
    import hashlib
    import json

    return json.dumps(
        {
            "n_rows": int(rows.shape[0]),
            "digest": hashlib.sha256(
                np.ascontiguousarray(rows).tobytes()
            ).hexdigest(),
            **extra,
        },
        sort_keys=True,
    )


def write_npz(batch: KVBatch, path: str) -> None:
    """Binary shard checkpoint: the packed device representation as-is."""
    np.savez_compressed(
        path,
        key_lanes=np.asarray(batch.key_lanes),
        values=np.asarray(batch.values),
        valid=np.asarray(batch.valid),
    )


def read_npz(path: str) -> KVBatch:
    import jax.numpy as jnp

    with np.load(path) as z:
        return KVBatch(
            key_lanes=jnp.asarray(z["key_lanes"]),
            values=jnp.asarray(z["values"]),
            valid=jnp.asarray(z["valid"]),
        )
