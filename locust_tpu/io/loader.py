"""Corpus ingest: text file -> NUL-padded uint8 line tensors.

Host-side replacement for ``loadFile`` (reference MapReduce/src/main.cu:40-64):
reads a text file line-by-line honoring a ``[line_start, line_end)`` slice for
per-node sharding (main.cu:47-54) and produces the padded ``[lines, width]``
uint8 tensor the device pipeline consumes.

Deliberate fixes vs the reference (SURVEY.md Appendix A):
  Q1 — the reference drops the final line (``*length = line_num - line_start``
       with a 0-based max index, main.cu:63); we count correctly.
  — no MAX_LINES_FILE_READ=5800 hard cap (main.cu:18): ingest streams; the
    engine blocks the corpus downstream.

A native C++ fast path (native/ingest.cpp, ctypes-loaded) handles large
corpora; this module is the always-available pure-Python fallback and the
single public API for both.
"""

from __future__ import annotations

import numpy as np

from locust_tpu.core import bytes_ops


def load_lines(
    path: str, line_start: int = -1, line_end: int = -1
) -> list[bytes]:
    """Read lines, applying the reference's [start, end) node-shard slice.

    ``line_start/line_end of -1`` means "whole file" (reference CLI default,
    main.cu:369-374).  Out-of-range ends clamp; start beyond EOF yields [].
    """
    with open(path, "rb") as f:
        data = f.read()
    lines = data.splitlines()
    if line_start < 0 and line_end < 0:
        return lines
    start = max(line_start, 0)
    end = len(lines) if line_end < 0 else min(line_end, len(lines))
    return lines[start:end]


def load_rows(
    path: str,
    line_width: int,
    line_start: int = -1,
    line_end: int = -1,
    use_native: bool = True,
) -> np.ndarray:
    """File -> padded ``[lines, line_width]`` uint8 rows (native if built)."""
    if use_native:
        try:
            from locust_tpu.io import native_ingest

            return native_ingest.load_rows(path, line_width, line_start, line_end)
        except (ImportError, OSError):
            pass
    return bytes_ops.strings_to_rows(
        load_lines(path, line_start, line_end), line_width
    )
