"""Corpus ingest: text file -> NUL-padded uint8 line tensors.

Host-side replacement for ``loadFile`` (reference MapReduce/src/main.cu:40-64):
reads a text file line-by-line honoring a ``[line_start, line_end)`` slice for
per-node sharding (main.cu:47-54) and produces the padded ``[lines, width]``
uint8 tensor the device pipeline consumes.

Deliberate fixes vs the reference (SURVEY.md Appendix A):
  Q1 — the reference drops the final line (``*length = line_num - line_start``
       with a 0-based max index, main.cu:63); we count correctly.
  — no MAX_LINES_FILE_READ=5800 hard cap (main.cu:18): ingest streams; the
    engine blocks the corpus downstream.

A native C++ fast path (native/ingest.cpp, ctypes-loaded) handles large
corpora; this module is the always-available pure-Python fallback and the
single public API for both.
"""

from __future__ import annotations

import numpy as np

from locust_tpu.core import bytes_ops


def load_lines(
    path: str, line_start: int = -1, line_end: int = -1
) -> list[bytes]:
    """Read lines, applying the reference's [start, end) node-shard slice.

    ``line_start/line_end of -1`` means "whole file" (reference CLI default,
    main.cu:369-374).  Out-of-range ends clamp; start beyond EOF yields [].

    Line semantics (canonical for every reader in this package, matching
    the reference's getline loop, main.cu:43-61): records split on ``\\n``
    ONLY; exactly one trailing ``\\r`` is stripped (CRLF).  A lone ``\\r``
    is data, not a separator — bytes.splitlines would disagree, which is
    why it is not used here.
    """
    with open(path, "rb") as f:
        data = f.read()
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()  # trailing newline, not an empty final record
    lines = [ln[:-1] if ln.endswith(b"\r") else ln for ln in lines]
    if line_start < 0 and line_end < 0:
        return lines
    start = max(line_start, 0)
    end = len(lines) if line_end < 0 else min(line_end, len(lines))
    return lines[start:end]


def measure_caps(lines) -> tuple[int, int]:
    """One host pass: (max token bytes, max tokens per line) over ``lines``.

    Feeds lossless capacity auto-sizing (``auto_caps`` below):
    ``key_width`` / ``emits_per_line`` set to these maxima change NOTHING
    about the output table relative to any larger caps — no token is
    truncated or dropped that the larger config would keep — they only
    shrink the fixed-shape arrays every sort and reduce pays for.

    Splits on the ENGINE's full delimiter set — ``DELIMITERS`` plus
    ``\\x00\\n\\r`` (core/bytes_ops.delimiter_mask) — not just the strtok
    set: a mid-line ``\\r`` or embedded NUL is data to the loader but a
    token boundary to the device tokenizer, and undercounting tokens
    here would let an auto-sized ``emits_per_line`` drop real emits.
    Deduplicates first: replicated corpora (the bench's) measure each
    unique line once.
    """
    import re

    from locust_tpu.config import FULL_DELIMITERS

    pat = re.compile(b"[" + re.escape(FULL_DELIMITERS) + b"]+")
    max_tok, max_per_line = 1, 1
    for ln in set(lines):
        toks = [t for t in pat.split(ln) if t]
        if toks:
            max_per_line = max(max_per_line, len(toks))
            max_tok = max(max_tok, max(len(t) for t in toks))
    return max_tok, max_per_line


def size_caps(
    max_tok: int, max_per_line: int, key_cap: int, emits_cap: int
) -> tuple[int, int]:
    """The one lossless sizing rule: measured maxima, lane-rounded key
    width (floor 8), never above the caller's caps."""
    kw = min(key_cap, max(8, -(-max_tok // 4) * 4))
    epl = min(emits_cap, max_per_line)
    return kw, epl


def count_distinct_tokens(lines) -> int:
    """Exact distinct-token count under the ENGINE's tokenization
    (FULL_DELIMITERS split, empties dropped), deduplicating lines first
    so replicated corpora count each unique line once.

    Upper-bounds the engine's distinct-key count: per-line emit
    overflow can only DROP tokens, and key-width truncation never
    applies when paired with ``auto_caps`` (key_width >= max token).  A
    table sized >= this count therefore cannot truncate — the guarantee
    bench.py's distinct-aware table sizing rests on.
    """
    import re

    from locust_tpu.config import FULL_DELIMITERS

    pat = re.compile(b"[" + re.escape(FULL_DELIMITERS) + b"]+")
    toks: set[bytes] = set()
    for ln in set(lines):
        toks.update(t for t in pat.split(ln) if t)
    return len(toks)


def auto_caps(lines, key_cap: int, emits_cap: int) -> tuple[int, int, int, int]:
    """Lossless capacity sizing: the single policy behind bench.py and
    ``--auto-caps`` (cli.py).

    Returns ``(key_width, emits_per_line, max_tok, max_per_line)`` with
    the caps at their measured lossless floors — max token bytes rounded
    up to a uint32 lane multiple (floor 8), max tokens/line — but never
    above the caller's ``key_cap`` / ``emits_cap``, so the output table
    is byte-identical to a run at the original caps.
    """
    max_tok, max_per_line = measure_caps(lines)
    kw, epl = size_caps(max_tok, max_per_line, key_cap, emits_cap)
    return kw, epl, max_tok, max_per_line


def measure_caps_rows(row_blocks) -> tuple[int, int]:
    """Bounded-memory (max token bytes, max tokens per line) over an
    iterable of padded ``[n, width]`` uint8 row blocks.

    The streaming analog of ``measure_caps`` — vectorized numpy per
    block, no dedup set, O(block) memory — so ``--auto-caps`` composes
    with ``--stream`` on corpora that don't fit RAM.  Tokenizes exactly
    as the device does: the full delimiter set incl. NUL (so the padding
    contributes nothing), scanning column-by-column (width ~128 steps of
    whole-block vector ops).
    """
    from locust_tpu.config import FULL_DELIMITERS

    lut = np.zeros(256, dtype=bool)
    for b in FULL_DELIMITERS:
        lut[b] = True
    max_tok, max_per_line = 1, 1
    for blk in row_blocks:
        rows = np.asarray(blk, dtype=np.uint8)
        if rows.size == 0:
            continue
        is_delim = lut[rows]                        # [n, w] bool
        starts = ~is_delim
        starts[:, 1:] &= is_delim[:, :-1]           # non-delim after delim
        max_per_line = max(max_per_line, int(starts.sum(axis=1).max()))
        run = np.zeros(rows.shape[0], dtype=np.int32)
        longest = np.zeros(rows.shape[0], dtype=np.int32)
        for c in range(rows.shape[1]):              # width steps, vector rows
            run = np.where(is_delim[:, c], 0, run + 1)
            np.maximum(longest, run, out=longest)
        max_tok = max(max_tok, int(longest.max()))
    return max_tok, max_per_line


def measure_caps_stream(stream) -> tuple[int, int]:
    """Caps measure for a ``StreamingCorpus``: native single-pass scan
    (``ingest_measure_caps`` — ~12x the numpy block path at 512MB scale)
    when the toolchain is available and the stream allows the native
    path (``use_native``, the same opt-out its block reader honors),
    else ``measure_caps_rows`` over the staged blocks.  Both measure the
    width-truncated [line_start, line_end) view; parity is pinned by
    tests/test_io.py."""
    if getattr(stream, "use_native", True):
        try:
            from locust_tpu.io import native_ingest

            return native_ingest.measure_caps(
                stream.path, stream.line_width,
                stream.line_start, stream.line_end,
            )
        except (ImportError, OSError):
            pass
    return measure_caps_rows(stream)


class _PrefetchError:
    """Wraps an exception crossing the reader thread (a private type no
    legitimate block iterator yields, so the isinstance check in
    ``prefetch_blocks`` cannot misfire on real items)."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch_blocks(blocks, depth: int = 2):
    """Iterate ``blocks`` with a daemon reader thread ``depth`` items ahead.

    Streaming folds alternate host file reads with device dispatches; the
    reader thread overlaps the next window's read+pad with the current
    fold's device time.  Semantically transparent: same items, same
    order, exceptions re-raised at the consuming ``next()``.  Memory grows
    by at most ``depth`` staged blocks.

    Abandoning the generator early (consumer raised mid-loop, e.g. a
    shuffle-overflow RuntimeError) stops the reader promptly: its puts
    poll a stop event, and the generator's ``finally`` sets it and drains
    the queue — no thread, source iterator, or staged blocks outlive the
    consumer (a leak per retry would accumulate in bench's TPU retry
    loop).
    """
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    end = object()
    stop = threading.Event()

    def put_or_stop(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def reader():
        try:
            for b in blocks:
                if not put_or_stop(b):
                    return
            put_or_stop(end)
        except BaseException as e:  # noqa: BLE001 - relayed to consumer
            put_or_stop(_PrefetchError(e))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is end:
                return
            if isinstance(item, _PrefetchError):
                raise item.exc
            yield item
    finally:
        stop.set()
        # Drain until the reader has exited: a single drain can race a
        # put that was already past the stop check, leaving one staged
        # block referenced by the queue until the daemon thread's next
        # loop iteration (ADVICE r3).  When the reader is blocked on a
        # put it polls stop every 0.1s, so a few join attempts suffice;
        # BOUNDED because a reader stalled inside next(blocks) (wedged
        # host read) never observes stop, and an unbounded join here
        # would trade a one-block reference for a permanent hang of the
        # consumer's own exception path.
        for _ in range(5):
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            if not t.is_alive():
                break
            t.join(timeout=0.2)
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


def count_lines(path: str) -> int:
    """Streaming line count (O(1) memory; multi-GB corpora are fine).

    The canonical trailing-fragment rule (Q1 semantics): a final line
    without a newline still counts.  Single source of truth — the
    distributor master and the native ingest parity tests both use this
    (VERDICT r2 weak #6: two drifting copies).
    """
    n = 0
    last = b"\n"
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            n += chunk.count(b"\n")
            last = chunk[-1:]
    if last != b"\n":
        n += 1
    return n


def load_rows(
    path: str,
    line_width: int,
    line_start: int = -1,
    line_end: int = -1,
    use_native: bool = True,
) -> np.ndarray:
    """File -> padded ``[lines, line_width]`` uint8 rows (native if built)."""
    if use_native:
        try:
            from locust_tpu.io import native_ingest

            return native_ingest.load_rows(path, line_width, line_start, line_end)
        except (ImportError, OSError):
            pass
    return bytes_ops.strings_to_rows(
        load_lines(path, line_start, line_end), line_width
    )


class StreamingCorpus:
    """Iterate ``[<=block_lines, line_width]`` row blocks of a file in
    bounded memory (VERDICT r2 missing #4).

    ``load_rows`` materializes the whole corpus — fine for hamlet, fatal
    for the 1GB+ north star (BASELINE.json).  This reader holds one
    ``chunk_bytes`` window plus one carried partial line at a time, the
    streaming upgrade of the reference's whole-file ``loadFile`` slicing
    (reference MapReduce/src/main.cu:40-64).  Uses the native windowed
    scanner (native/ingest.cpp ``ingest_load_window``) when built, else a
    pure-Python chunked read; both honor the ``[line_start, line_end)``
    node-shard slice.

    A line longer than ``chunk_bytes`` is truncated to ``line_width``
    (the device contract anyway) and its remainder skipped — progress is
    guaranteed for any input.

    Iterating yields numpy arrays; every block except possibly the last
    has exactly ``block_lines`` rows.  ``fingerprint()`` hashes identity
    metadata + first window content for checkpoint/resume without a full
    read.
    """

    def __init__(
        self,
        path: str,
        line_width: int,
        block_lines: int,
        line_start: int = -1,
        line_end: int = -1,
        chunk_bytes: int = 32 << 20,
        use_native: bool = True,
    ):
        if block_lines < 1 or line_width < 1:
            raise ValueError("block_lines and line_width must be >= 1")
        self.path = path
        self.line_width = line_width
        self.block_lines = block_lines
        self.line_start = line_start
        self.line_end = line_end
        self.chunk_bytes = max(chunk_bytes, 1 << 16)
        self.use_native = use_native

    def fingerprint(self) -> str:
        """Cheap corpus identity: path + size + mtime + head digest."""
        import hashlib
        import os

        st = os.stat(self.path)
        h = hashlib.sha256()
        with open(self.path, "rb") as f:
            h.update(f.read(1 << 20))
        return (
            f"{os.path.abspath(self.path)}:{st.st_size}:{st.st_mtime_ns}:"
            f"{h.hexdigest()[:16]}:{self.line_start}:{self.line_end}"
        )

    def __iter__(self):
        if self.use_native:
            # Fall back to the Python reader ONLY if the native path fails
            # before producing anything; a mid-stream error after blocks
            # were already yielded must propagate — restarting from the top
            # would silently double-count every already-folded block.
            started = False
            try:
                from locust_tpu.io import native_ingest

                for blk in native_ingest.iter_blocks(
                    self.path,
                    self.line_width,
                    self.block_lines,
                    self.line_start,
                    self.line_end,
                ):
                    started = True
                    yield blk
                return
            except (ImportError, OSError):
                if started:
                    raise
        yield from self._iter_python()

    def _iter_python(self):
        start = max(self.line_start, 0) if self.line_start >= 0 else 0
        end = self.line_end if self.line_end >= 0 else None
        line_no = 0
        pending: list[bytes] = []
        carry = b""
        with open(self.path, "rb") as f:
            while True:
                chunk = f.read(self.chunk_bytes)
                if not chunk:
                    break
                data = carry + chunk
                lines = data.split(b"\n")
                carry = lines.pop()  # partial (or empty) trailing piece
                if len(carry) > self.line_width:
                    # Keep only the prefix the device can see (the row is
                    # truncated to line_width anyway); bounds memory for
                    # pathologically long lines while the rest streams past.
                    carry = carry[: self.line_width]
                for ln in lines:
                    if end is not None and line_no >= end:
                        break
                    if line_no >= start:
                        pending.append(ln[:-1] if ln.endswith(b"\r") else ln)
                    line_no += 1
                    if len(pending) >= self.block_lines:
                        yield bytes_ops.strings_to_rows(
                            pending[: self.block_lines], self.line_width
                        )
                        pending = pending[self.block_lines :]
                if end is not None and line_no >= end:
                    carry = b""
                    break
        if carry and (end is None or line_no < end):
            if line_no >= start:
                pending.append(carry[:-1] if carry.endswith(b"\r") else carry)
        while pending:
            yield bytes_ops.strings_to_rows(
                pending[: self.block_lines], self.line_width
            )
            pending = pending[self.block_lines :]
