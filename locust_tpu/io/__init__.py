from locust_tpu.io import loader, serde, snapshot  # noqa: F401
