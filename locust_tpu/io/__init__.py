from locust_tpu.io import loader, serde  # noqa: F401
