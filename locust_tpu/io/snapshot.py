"""Asynchronous, bounded checkpoint writing for the streaming tier.

The reference's persistence story is synchronous by construction — "map
wrote /tmp/out.txt, re-run reduce from it" (reference
MapReduce/src/main.cu:428-441).  Our streaming folds used to inherit that
shape: ``Engine._save_state`` and ``ShardedCheckpoint.snapshot`` did the
device->host snapshot plus the compressed npz write INSIDE the fold
loop, stalling the device pipeline once per checkpoint cadence.  This
module is the tf.data-style fix (Murray et al., VLDB '21: keep the
accelerator busy by moving host byte movement off the critical path):

  * the hot loop only MARKS a generation — an on-device copy of the
    accumulator (cheap, async) plus a closure that can serialize it;
  * a single daemon writer thread waits on that specific fold's
    readiness (the device->host copy inside the closure blocks until the
    marked fold completed), serializes, and atomically renames;
  * the queue is bounded to ONE pending generation, latest-wins: if the
    loop laps the writer, intermediate generations are skipped — a
    resume then re-reads (but never re-folds) a few more blocks, which
    is exactly the durability/throughput trade a checkpoint cadence
    already expresses.

Crash consistency is unchanged relative to the synchronous writers: every
snapshot still lands as one atomically-replaced npz (tmp write + fsync-
free ``os.replace``, same as before), so the state file is always some
COMPLETE generation; the ``io.ckpt_write`` fault site injects a writer
crash between the tmp write and the rename (the new failure point the
async path adds) and the chaos matrix (tests/test_faults.py) pins that
the run's output stays byte-identical and a resume over the debris stays
exact.

Error discipline: a FaultInjected "crash" models the writer dying — the
snapshot is abandoned (old generation survives; durability, not
correctness) and the run continues.  Any OTHER writer exception (disk
full, permission) is recorded and re-raised on the submitting thread at
the next ``submit()``/``flush()`` — real failures stay loud, just like
the synchronous path, at most one cadence late.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from locust_tpu import obs
from locust_tpu.utils import faultplan

logger = logging.getLogger("locust_tpu")


def finalize_snapshot(tmp: str, path: str, prev_path: str | None = None,
                      generation: int | None = None) -> None:
    """Publish a fully-written ``tmp`` snapshot at ``path`` atomically.

    The ONE copy of the publish protocol shared by the single-device
    engine and ``ShardedCheckpoint``: optional previous-generation
    rotation, the ``io.ckpt_write`` chaos hook at the new async failure
    point (crash/delay between serialization and rename), the atomic
    ``os.replace``, then the pre-existing ``io.checkpoint`` damage hook
    on the published file.  A "crash" fault leaves ``tmp`` behind and
    ``path`` at its previous generation — exactly the debris a writer
    thread dying at that instant would leave.
    """
    rule = faultplan.fire("io.ckpt_write", path=path, generation=generation)
    if rule is not None:
        if rule.action == "delay" and rule.delay_s > 0:
            time.sleep(rule.delay_s)
        elif rule.action == "crash":
            raise faultplan.FaultCrash(
                f"[faultplan] injected checkpoint-writer crash before "
                f"rename of {path} (generation {generation})"
            )
    if prev_path is not None and os.path.exists(path):
        os.replace(path, prev_path)
    os.replace(tmp, path)
    # Telemetry: the generation is durable from this instant (the
    # checkpoint-lifecycle event resumes reason about).
    obs.event("ckpt.publish", generation=generation, path=path)
    # Post-publish bit-rot/truncation chaos (no-op without an active
    # plan) — loaders must validate and fall back.
    faultplan.damage_file("io.checkpoint", path)


class AsyncCheckpointWriter:
    """Bounded background snapshot writer, one pending generation deep.

    ``submit(generation, write_fn)`` replaces any still-pending
    generation (latest-wins) and returns immediately; the daemon thread
    runs ``write_fn()`` — which owns waiting for device readiness, the
    device->host copy, serialization, and the atomic rename — strictly
    serially, so two generations can never interleave their tmp files.
    ``flush()`` blocks until nothing is pending or in flight and
    re-raises any recorded writer error; ``close()`` flushes best-effort
    and joins the thread, never raising (safe in ``finally`` blocks).

    Stats (all under the one lock): ``submitted`` marks, ``written``
    snapshots, ``skipped`` generations replaced while pending (the loop
    lapped the writer), ``abandoned`` injected-crash writes, and
    ``max_lag`` — measured at WRITE COMPLETION as how many generations
    the just-published snapshot trails the newest mark (0 = the writer
    is keeping up; positive = the loop lapped it by that many blocks) —
    the "checkpoint lag" the bench reports.
    """

    def __init__(self, name: str = "ckpt-writer"):
        # Telemetry scope captured at CREATION (the fold-loop thread):
        # the writer daemon's ckpt.write/ckpt.publish must land in the
        # same tracer as the loop's ckpt.mark — a worker's request-scoped
        # tracer, not the process tracer of whoever shares the process
        # (loopback clusters: without this, worker checkpoint writes
        # would misattribute to the MASTER's timeline).
        self._obs_tracer = obs.current()
        self._cond = threading.Condition()
        self._pending: tuple[int, object] | None = None
        self._busy = False
        self._closed = False
        self._error: BaseException | None = None
        self._submitted = 0
        self._written = 0
        self._skipped = 0
        self._abandoned = 0
        self._latest_gen = 0
        self._max_lag = 0
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ api

    def submit(self, generation: int, write_fn) -> None:
        """Mark ``generation`` for writing; replaces any pending mark."""
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            if self._pending is not None:
                self._skipped += 1
                # Latest-wins lap: the replaced generation never lands.
                obs.event("ckpt.skip", generation=self._pending[0],
                          replaced_by=generation)
            self._pending = (generation, write_fn)
            self._submitted += 1
            self._latest_gen = max(self._latest_gen, generation)
            self._cond.notify_all()

    def flush(self, raise_errors: bool = True,
              timeout: float | None = None) -> bool:
        """Wait until the writer is idle (or ``timeout`` seconds passed);
        surface any recorded error.  Returns True if the writer is idle."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending is not None or self._busy:
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                self._cond.wait(timeout=0.5)
            if raise_errors and self._error is not None:
                err, self._error = self._error, None
                raise err
            return True

    def close(self) -> None:
        """Flush best-effort (BOUNDED — a write_fn wedged on a dead
        device link must not turn the caller's ``finally`` into a hang)
        and stop the thread.  Never raises; on timeout the daemon thread
        is abandoned mid-write (the tmp-then-rename protocol means the
        state file still holds a complete generation)."""
        try:
            if not self.flush(raise_errors=False, timeout=30.0):
                logger.warning(
                    "async checkpoint writer still busy at close; "
                    "abandoning the in-flight write (daemon thread)"
                )
        except Exception:  # pragma: no cover - flush never raises here
            logger.warning(
                "async checkpoint close: flush raised unexpectedly "
                "(contract says it never does); abandoning the in-flight "
                "write", exc_info=True,
            )
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=10.0)

    def stats(self) -> dict:
        with self._cond:
            return {
                "submitted": self._submitted,
                "written": self._written,
                "skipped": self._skipped,
                "abandoned": self._abandoned,
                "max_lag": self._max_lag,
            }

    # --------------------------------------------------------------- worker

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait()
                if self._pending is None and self._closed:
                    return
                generation, fn = self._pending
                self._pending = None
                self._busy = True
                self._cond.notify_all()
            abandoned = False
            error = None
            try:
                # Span covers the writer's whole generation: device-ready
                # wait + device->host copy + npz write + atomic publish —
                # recorded into the creator's tracer (see __init__).
                with obs.scoped(self._obs_tracer):
                    with obs.span("ckpt.write", generation=generation):
                        fn()
            except faultplan.FaultInjected as e:
                # An injected writer crash: the snapshot is abandoned and
                # the previous generation survives on disk — durability
                # lost for one cadence, correctness untouched.
                abandoned = True
                logger.warning(
                    "checkpoint writer crash injected at generation %d "
                    "(%s); snapshot abandoned", generation, e,
                )
            except BaseException as e:  # noqa: BLE001 - relayed to submitter
                error = e
                logger.warning(
                    "async checkpoint write failed at generation %d "
                    "(%s: %s)", generation, type(e).__name__, e,
                )
            with self._cond:
                self._busy = False
                if abandoned:
                    self._abandoned += 1
                elif error is not None:
                    self._error = error
                else:
                    self._written += 1
                    # Lag at publish time: how far the newest mark has
                    # run ahead of the generation that just became
                    # durable.  0 for a writer that keeps up.
                    self._max_lag = max(
                        self._max_lag, self._latest_gen - generation
                    )
                self._cond.notify_all()
