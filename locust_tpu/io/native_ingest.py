"""ctypes bindings for the native ingest library (native/ingest.cpp).

Builds the shared object on first use with the system g++ (cached in
``native/build/``); callers go through io/loader.load_rows which falls back
to the pure-Python path if the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_SRC = _NATIVE_DIR / "ingest.cpp"
_SO = _NATIVE_DIR / "build" / "libingest.so"

_lock = threading.Lock()
_lib = None


def _build() -> pathlib.Path:
    _SO.parent.mkdir(parents=True, exist_ok=True)
    if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", str(_SO), str(_SRC)],
            check=True,
            capture_output=True,
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        # Surface as OSError so io/loader falls back to the Python path.
        raise OSError(f"native ingest build failed: {e}") from e
    return _SO


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(str(_build()))
            lib.ingest_count_lines.restype = ctypes.c_long
            lib.ingest_count_lines.argtypes = [ctypes.c_char_p]
            lib.ingest_load_rows.restype = ctypes.c_long
            lib.ingest_load_rows.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.c_long,
                ctypes.c_long,
                ctypes.c_long,
                ctypes.c_long,
            ]
            lib.ingest_load_window.restype = ctypes.c_long
            lib.ingest_load_window.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.c_long,
                ctypes.c_long,
                ctypes.c_long,
                ctypes.c_long,
            ]
            lib.ingest_measure_caps.restype = ctypes.c_long
            lib.ingest_measure_caps.argtypes = [
                ctypes.c_char_p,
                ctypes.c_long,
                ctypes.c_long,
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.c_long,
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
            ]
            lib.ingest_read_tsv.restype = ctypes.c_long
            lib.ingest_read_tsv.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.POINTER(ctypes.c_int),
                ctypes.c_long,
                ctypes.c_long,
            ]
            _lib = lib
    return _lib


def measure_caps(
    path: str, width: int, line_start: int = -1, line_end: int = -1
) -> tuple[int, int]:
    """Single-pass (max token bytes, max tokens/line) over the
    width-truncated [line_start, line_end) slice — the native fast path
    behind io/loader.measure_caps_stream.  The delimiter set travels from
    config.FULL_DELIMITERS so it can never drift from the device
    tokenizer."""
    from locust_tpu.config import FULL_DELIMITERS

    lib = _load()
    delims = (ctypes.c_ubyte * len(FULL_DELIMITERS)).from_buffer_copy(
        FULL_DELIMITERS
    )
    max_tok = ctypes.c_long(0)
    max_per_line = ctypes.c_long(0)
    rc = lib.ingest_measure_caps(
        str(path).encode(),
        width,
        line_start,
        line_end,
        delims,
        len(FULL_DELIMITERS),
        ctypes.byref(max_tok),
        ctypes.byref(max_per_line),
    )
    if rc != 0:
        raise OSError(f"native measure_caps failed on {path!r}")
    return int(max_tok.value), int(max_per_line.value)


def count_lines(path: str) -> int:
    n = _load().ingest_count_lines(str(path).encode())
    if n < 0:
        raise OSError(f"native ingest failed to read {path!r}")
    return n


def load_rows(
    path: str, line_width: int, line_start: int = -1, line_end: int = -1
) -> np.ndarray:
    """File -> padded [rows, line_width] uint8, sliced [line_start, line_end)."""
    lib = _load()
    total = count_lines(path)
    start = max(line_start, 0) if line_start >= 0 else 0
    end = total if line_end < 0 else min(line_end, total)
    n_rows = max(end - start, 0)
    out = np.zeros((n_rows, line_width), dtype=np.uint8)
    if n_rows == 0:
        return out
    wrote = lib.ingest_load_rows(
        str(path).encode(),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        n_rows,
        line_width,
        line_start,
        line_end,
    )
    if wrote < 0:
        raise OSError(f"native ingest failed to read {path!r}")
    return out[:wrote] if wrote < n_rows else out


def read_tsv(path: str, key_width: int) -> tuple[np.ndarray, np.ndarray]:
    """Native "key\\tvalue" TSV parse -> (padded key rows, int32 values).

    Two passes over the file (count, then fill) with a fixed 1MB buffer —
    semantics identical to io/serde.read_tsv's Python path (parity-tested).
    """
    lib = _load()

    def check(rc: int) -> int:
        if rc == -2:
            # Same exception class as the Python path's int32 check.
            raise OverflowError(f"TSV value in {path!r} does not fit int32")
        if rc < 0:
            raise OSError(f"native TSV read failed for {path!r}")
        return rc

    null_keys = ctypes.POINTER(ctypes.c_ubyte)()
    null_vals = ctypes.POINTER(ctypes.c_int)()
    n = check(
        lib.ingest_read_tsv(str(path).encode(), null_keys, null_vals, 0, key_width)
    )
    keys = np.zeros((n, key_width), dtype=np.uint8)
    values = np.zeros((n,), dtype=np.int32)
    if n:
        wrote = check(
            lib.ingest_read_tsv(
                str(path).encode(),
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
                values.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
                n,
                key_width,
            )
        )
        if wrote < n:  # file shrank between passes
            keys, values = keys[:wrote], values[:wrote]
    return keys, values


def iter_blocks(
    path: str,
    line_width: int,
    block_lines: int,
    line_start: int = -1,
    line_end: int = -1,
):
    """Yield ``[<=block_lines, line_width]`` row blocks via the native
    windowed scanner (bounded memory; see ingest.cpp ingest_load_window)."""
    lib = _load()
    offset = ctypes.c_long(0)
    line_no = ctypes.c_long(0)
    while True:
        out = np.zeros((block_lines, line_width), dtype=np.uint8)
        wrote = lib.ingest_load_window(
            str(path).encode(),
            ctypes.byref(offset),
            ctypes.byref(line_no),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            block_lines,
            line_width,
            line_start,
            line_end,
        )
        if wrote < 0:
            raise OSError(f"native ingest failed to read {path!r}")
        if wrote == 0:
            return
        yield out[:wrote] if wrote < block_lines else out
