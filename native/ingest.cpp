// Native corpus ingest: file -> NUL-padded fixed-width line rows.
//
// TPU-native equivalent of the reference's host ingest (loadFile,
// reference MapReduce/src/main.cu:40-64): the reference reads with a
// getline loop into 204-byte structs; here one buffered read + a single
// scan splits lines and pads them straight into the caller's contiguous
// [max_lines, width] uint8 buffer, which the Python side hands to
// jnp.asarray with zero further copies.  Honors the same [line_start,
// line_end) node-shard slice (main.cu:47-54) and fixes the reference's
// dropped-final-line off-by-one (SURVEY.md Q1).
//
// Exposed via a C ABI for ctypes (no pybind11 in this toolchain).

#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// Reads the whole file; returns malloc'd buffer (caller frees) or nullptr.
char* read_file(const char* path, long* size_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  char* buf = static_cast<char*>(std::malloc(size > 0 ? size : 1));
  if (!buf) {
    std::fclose(f);
    return nullptr;
  }
  long got = static_cast<long>(std::fread(buf, 1, size, f));
  std::fclose(f);
  if (got != size) {
    std::free(buf);
    return nullptr;
  }
  *size_out = size;
  return buf;
}

}  // namespace

extern "C" {

// Number of lines in the file ('\n'-separated; a trailing fragment without
// a newline counts — the Q1 fix).  Returns -1 on I/O error.
long ingest_count_lines(const char* path) {
  long size = 0;
  char* buf = read_file(path, &size);
  if (!buf) return -1;
  long lines = 0;
  bool in_line = false;
  for (long i = 0; i < size; ++i) {
    if (buf[i] == '\n') {
      ++lines;
      in_line = false;
    } else {
      in_line = true;
    }
  }
  if (in_line) ++lines;
  std::free(buf);
  return lines;
}

// Load lines [line_start, line_end) into out[max_lines][width], NUL-padded,
// '\r' stripped at line end, content truncated to width.  Negative
// start/end mean "whole file" (reference CLI default, main.cu:369-374).
// Returns rows written, or -1 on I/O error.
long ingest_load_rows(const char* path, unsigned char* out, long max_lines,
                      long width, long line_start, long line_end) {
  long size = 0;
  char* buf = read_file(path, &size);
  if (!buf) return -1;
  long start = line_start < 0 ? 0 : line_start;
  long end = line_end < 0 ? -1 : line_end;  // -1 = unbounded

  std::memset(out, 0, static_cast<size_t>(max_lines) * width);
  long line = 0, row = 0;
  long pos = 0;
  while (pos <= size - 1 || (pos == 0 && size == 0)) {
    if (pos >= size) break;
    // Find line extent [pos, eol).
    long eol = pos;
    while (eol < size && buf[eol] != '\n') ++eol;
    if (line >= start && (end < 0 || line < end) && row < max_lines) {
      long len = eol - pos;
      if (len > 0 && buf[pos + len - 1] == '\r') --len;  // CRLF
      if (len > width) len = width;
      std::memcpy(out + row * width, buf + pos, len);
      ++row;
    }
    ++line;
    pos = eol + 1;
    if (end >= 0 && line >= end) break;
  }
  std::free(buf);
  return row;
}

// Streaming window scan: resume at byte *inout_offset / line *inout_line,
// fill out[max_lines][width] (NUL-padded, '\r' stripped, truncated to
// width), honoring the [line_start, line_end) slice.  Advances the two
// cursors to the exact resume point (always a line boundary) and returns
// rows written — 0 means EOF or slice end.  Unlike ingest_load_rows, the
// file is NEVER materialized: one fixed 1MB read buffer regardless of
// file or line length (a line longer than the buffer keeps only its first
// `width` bytes while the remainder streams past), which is what lets the
// 1GB+ north-star corpus (BASELINE.json) run in bounded RSS.
long ingest_load_window(const char* path, long* inout_offset,
                        long* inout_line, unsigned char* out, long max_lines,
                        long width, long line_start, long line_end) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  if (std::fseek(f, *inout_offset, SEEK_SET) != 0) {
    std::fclose(f);
    return -1;
  }
  const long start = line_start < 0 ? 0 : line_start;
  const long end = line_end;  // < 0 = unbounded
  long line = *inout_line;
  long row = 0;
  long consumed = 0;  // bytes folded into COMPLETED (or EOF-final) lines
  long linelen = 0;   // bytes seen of the in-progress line
  std::memset(out, 0, static_cast<size_t>(max_lines) * width);

  const long B = 1 << 20;
  unsigned char* buf = static_cast<unsigned char*>(std::malloc(B));
  if (!buf) {
    std::fclose(f);
    return -1;
  }
  bool done = false;
  bool in_line = false;
  while (!done) {
    long got = static_cast<long>(std::fread(buf, 1, B, f));
    if (got <= 0) break;  // EOF
    for (long i = 0; i < got; ++i) {
      const bool want = line >= start && (end < 0 || line < end);
      if (end >= 0 && line >= end) {
        done = true;
        break;
      }
      if (!in_line && want && row >= max_lines) {
        done = true;  // capacity reached at a line boundary: resume here
        break;
      }
      const unsigned char c = buf[i];
      ++consumed;
      if (c == '\n') {
        if (want) {
          long len = linelen < width ? linelen : width;
          // Strip the CRLF '\r' only when it actually is the line's last
          // byte; at a truncated position (linelen > width) it is data.
          if (linelen <= width && len > 0 &&
              out[row * width + len - 1] == '\r')
            out[row * width + len - 1] = 0;
          ++row;
        }
        ++line;
        linelen = 0;
        in_line = false;
      } else {
        in_line = true;
        if (want && linelen < width) out[row * width + linelen] = c;
        ++linelen;
      }
    }
  }
  if (in_line && !done) {  // trailing fragment without '\n' (Q1 fix)
    const bool want = line >= start && (end < 0 || line < end);
    if (want && row < max_lines) {
      long len = linelen < width ? linelen : width;
      if (linelen <= width && len > 0 && out[row * width + len - 1] == '\r')
        out[row * width + len - 1] = 0;
      ++row;
    }
    ++line;
  }
  std::free(buf);
  std::fclose(f);
  *inout_offset += consumed;
  *inout_line = line;
  return row;
}

// Single-pass streaming caps measure: max token bytes + max tokens/line
// over the WIDTH-TRUNCATED view of each line in [line_start, line_end) —
// the same measurement io/loader.measure_caps_rows makes over staged row
// blocks (a token is a maximal run of non-delimiter bytes within the
// first `width` bytes; bytes past the truncation point are invisible, so
// a run caps there and later tokens on the line don't exist).  The
// delimiter set is PASSED IN (config.FULL_DELIMITERS) — a hardcoded copy
// here would drift from the device tokenizer and let --auto-caps
// under-size emits_per_line.  '\r' needs no special case: the windowed
// loader strips a trailing CR, but CR is in the delimiter set so a
// stripped-vs-kept CR closes the same token either way.  Floors are
// (1, 1) like the Python sites.  Returns 0, or -1 on I/O error.
long ingest_measure_caps(const char* path, long width, long line_start,
                         long line_end, const unsigned char* delims,
                         long n_delims, long* out_max_tok,
                         long* out_max_per_line) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  bool lut[256] = {false};
  for (long i = 0; i < n_delims; ++i) lut[delims[i]] = true;
  lut[static_cast<unsigned char>('\n')] = true;  // line terminator anyway

  const long B = 1 << 20;
  unsigned char* buf = static_cast<unsigned char*>(std::malloc(B));
  if (!buf) {
    std::fclose(f);
    return -1;
  }
  const long start = line_start < 0 ? 0 : line_start;
  const long end = line_end;  // < 0 = unbounded
  long line = 0, pos = 0, run = 0, toks = 0;
  long max_tok = 1, max_per_line = 1;
  bool in_line = false;
  bool done = false;

  // Close the current token run / line, folding into the maxima.
  auto close_run = [&]() {
    if (run > max_tok) max_tok = run;
    run = 0;
  };
  auto close_line = [&]() {
    close_run();
    if (toks > max_per_line) max_per_line = toks;
    ++line;
    pos = 0;
    toks = 0;
    in_line = false;
  };

  while (!done) {
    long got = static_cast<long>(std::fread(buf, 1, B, f));
    if (got <= 0) {
      // A mid-file read ERROR must not return caps measured from a
      // prefix — silently undersized caps would drop real emits.
      if (std::ferror(f)) {
        std::free(buf);
        std::fclose(f);
        return -1;
      }
      break;  // clean EOF
    }
    for (long i = 0; i < got; ++i) {
      if (end >= 0 && line >= end) {
        done = true;
        break;
      }
      const unsigned char c = buf[i];
      if (c == '\n') {
        close_line();
        continue;
      }
      in_line = true;
      const bool want = line >= start;
      if (want && pos < width) {
        if (lut[c]) {
          close_run();
        } else {
          if (run == 0) ++toks;
          ++run;
        }
      }
      ++pos;
    }
  }
  if (in_line && !done) close_line();  // trailing fragment (Q1 semantics)
  std::free(buf);
  std::fclose(f);
  *out_max_tok = max_tok;
  *out_max_per_line = max_per_line;
  return 0;
}

// Streaming "key\tvalue" TSV parser — the native fast path for the
// reduce stage's intermediate loads (python analog: io/serde.read_tsv;
// reference analog: loadIntermediateFile, main.cu:66-103).  Semantics
// must match serde.read_tsv EXACTLY (parity-tested):
//   * split each line at the FIRST tab,
//   * strip trailing ' ' from the key (the reference writes "key \t", Q5)
//     — at the key's true end only, not at the width-truncation point,
//   * keys NUL-pad / truncate to key_width,
//   * values parse as base-10 ints with surrounding whitespace tolerated
//     (python int()); malformed values and empty keys skip the row,
//   * blank lines skip; '\r' before '\n' is stripped.
// Bounded memory: one fixed 1MB read buffer; per-line state carries only
// the first key_width key bytes and a small value buffer.
// Call with out_keys == NULL to COUNT parseable rows (pass 1), then with
// buffers sized [count, key_width] / [count] to fill (pass 2).
// Returns rows parsed/filled, or -1 on I/O error.
long ingest_read_tsv(const char* path, unsigned char* out_keys,
                     int* out_values, long max_rows, long key_width) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  const long B = 1 << 20;
  unsigned char* buf = static_cast<unsigned char*>(std::malloc(B));
  if (!buf) {
    std::fclose(f);
    return -1;
  }
  const bool counting = out_keys == nullptr;
  long rows = 0;
  bool range_error = false;  // a value outside int32: hard error (-2)

  // Per-line state.  VMAX bounds a VALUE field; longer fields are
  // malformed rows in BOTH parsers (the strict grammar below).
  const int VMAX = 63;
  unsigned char keybuf[256];  // key prefix (key_width <= 256 enforced)
  unsigned char valbuf[VMAX];
  long klen = 0;        // total key bytes seen
  long last_ns = -1;    // index of last non-space key byte
  int vlen = 0;
  long pending_cr = 0;  // run of '\r' that may be the CRLF terminator
  bool in_value = false;
  bool val_too_long = false;
  if (key_width > 256) {
    std::free(buf);
    std::fclose(f);
    return -1;
  }

  auto isws = [](unsigned char c) {
    return c == ' ' || c == '\t' || c == '\r';
  };

  auto finish_line = [&]() {
    long eff = last_ns + 1;  // key length after trailing-space strip
    bool ok = eff > 0 && in_value && !val_too_long;
    long long value = 0;
    if (ok) {
      // The STRICT value grammar both parsers implement:
      //   [ws]* [+-]? [0-9]+ [ws]*      (ws = ' ' '\t' '\r')
      // Anything else (letters, NULs, underscores, second tabs) skips
      // the row; a syntactically valid value outside int32 is a HARD
      // error for the whole file (silent wrap would corrupt counts).
      int j = 0;
      while (j < vlen && isws(valbuf[j])) ++j;
      long long sign = 1;
      if (j < vlen && (valbuf[j] == '+' || valbuf[j] == '-')) {
        sign = valbuf[j] == '-' ? -1 : 1;
        ++j;
      }
      const int digits_start = j;
      while (j < vlen && valbuf[j] >= '0' && valbuf[j] <= '9') {
        if (value < (1LL << 40))  // keep accumulating until clearly over
          value = value * 10 + (valbuf[j] - '0');
        ++j;
      }
      if (j == digits_start) ok = false;  // no digits
      while (j < vlen && isws(valbuf[j])) ++j;
      if (j != vlen) ok = false;  // trailing junk (incl. NUL bytes)
      value *= sign;
      if (ok && (value > 2147483647LL || value < -2147483648LL))
        range_error = true;
    }
    if (ok && !range_error) {
      if (!counting && rows < max_rows) {
        long keep = eff < key_width ? eff : key_width;
        std::memset(out_keys + rows * key_width, 0,
                    static_cast<size_t>(key_width));
        std::memcpy(out_keys + rows * key_width, keybuf,
                    static_cast<size_t>(keep));
        out_values[rows] = static_cast<int>(value);
        ++rows;
      } else if (counting) {
        ++rows;
      }
    }
    klen = 0;
    last_ns = -1;
    vlen = 0;
    pending_cr = 0;
    in_value = false;
    val_too_long = false;
  };

  for (;;) {
    long got = static_cast<long>(std::fread(buf, 1, B, f));
    if (got <= 0) break;
    for (long i = 0; i < got && !range_error; ++i) {
      const unsigned char c = buf[i];
      if (c == '\n') {
        finish_line();
      } else if (!in_value) {
        if (c == '\t') {
          in_value = true;
        } else {
          if (c != ' ') last_ns = klen;  // only ' ' strips from key tails (Q5)
          if (klen < key_width) keybuf[klen] = c;
          ++klen;
        }
      } else {
        // Trailing '\r' runs are the line terminator, not value bytes
        // (the Python path rstrips them from the LINE before its length
        // check); only '\r's later followed by a non-'\r' byte are value
        // content and count toward the field budget.
        if (c == '\r') {
          ++pending_cr;
        } else {
          while (pending_cr > 0 && vlen < VMAX) {
            valbuf[vlen++] = '\r';
            --pending_cr;
          }
          if (pending_cr > 0) val_too_long = true;
          pending_cr = 0;
          if (vlen < VMAX) valbuf[vlen++] = c;
          else val_too_long = true;
        }
      }
    }
    if (range_error) break;
  }
  const bool io_error = std::ferror(f) != 0;
  if (!range_error && !io_error && (klen > 0 || in_value))
    finish_line();  // trailing line without '\n'
  std::free(buf);
  std::fclose(f);
  if (io_error) return -1;       // mid-file read error, NOT a short file
  if (range_error) return -2;    // int32 overflow in a value
  return rows;
}

}  // extern "C"
