"""TPU sweep phases 2.5 -> 4, shared by the full sweep and window-resume.

``scripts/tpu_opportunistic.py`` (the full sweep: phases 1-2 are separate
subprocesses, then these) imports the phase functions below — they exist
in exactly ONE place so evidence rows can't diverge between the two entry
points.  Run this file directly to resume a window where phases 1/2
already recorded (their rows are append-only in artifacts/tpu_runs.jsonl
and their compiles are the expensive part to re-pay).

Usage:  python scripts/opp_resume.py            # stage parity + A/Bs
        LOCUST_OPP_STREAM_MB=512 python scripts/opp_resume.py  # + streaming

Same artifact rows as the main sweep; safe to run repeatedly.
"""

import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from locust_tpu.config import (  # noqa: E402 - jax-free
    default_sort_mode,
    machine_cache_dir,
)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", machine_cache_dir())

# Engine sort modes covered by the end-to-end A/B (phase 3).
# Priority order: a short window should answer the open questions first —
# the sort-free hasht fold (VERDICT r4 next #2), then the fused Pallas
# megakernel "fused" (ROADMAP item 5: the mode that DELETES the token
# tensor's HBM round-trip — the highest-expected-value unknown since the
# hasht rows, modeled strictly below hasht-mxu's bytes; zero TPU
# measurements yet), then the MXU-combine variant hasht-mxu (VERDICT r5
# item 8), then the measured winner hashp2 so the window always
# re-anchors the incumbent — before re-timing the also-rans.  The Pallas
# bitonic kernel is DEMOTED to last (VERDICT r5 item 4: a 1.26x loser
# with a 100.7 s compile; its tile/fusion ladders are retired from the
# check battery outright, docs/PERF.md) — the fused megakernel carries
# the hand-written-kernel thesis now; tests pin the ordering.
AB_SORT_MODES = ("hasht", "fused", "hasht-mxu", "hashp2", "hashp1", "hashp",
                 "hash", "hash1", "radix", "bitonic")

# The first-slot subset scripts/tpu_opportunistic.py measures BEFORE any
# other phase spends window seconds (fused's engine-level verdict must
# land even in a window that dies minutes in; rows are ordinary
# engine_sort_mode_ab rows, so phase 3 resumes past them for free).
FUSED_AB_MODES = ("hasht", "fused", "hasht-mxu")

# The second-slot streaming verdict (megakernel v2): the persistent
# streaming kernel vs plain hasht through run_stream.  Distinct mode
# labels so the rows share the engine_sort_mode_ab shape (and
# _prior_mode_results' resume) without ever colliding with the batch
# modes above.
FUSED_STREAM_AB_MODES = ("fused_stream", "hasht_stream")

# Engines memoized by their frozen EngineConfig: several phases measure
# the SAME winning configuration (block A/B winner -> pallas False side
# -> profiler capture -> bench-shape stage breakdown), and a fresh
# MapReduceEngine means fresh jit closures = a full recompile through
# the axon tunnel (~20-40s each; the remote backend never serializes a
# cache).  Reusing the engine reuses its compiled executables — worth
# ~1-2 minutes of a short window.
_ENGINES: dict = {}


def get_engine(cfg):
    from locust_tpu.engine import MapReduceEngine

    eng = _ENGINES.get(cfg)
    if eng is None:
        eng = _ENGINES.setdefault(cfg, MapReduceEngine(cfg))
    return eng


def tunnel_gate() -> bool:
    """Probe the TPU tunnel and select the backend; False = tunnel down.
    The single gate both sweep entry points run behind."""
    from locust_tpu.backend import probe_tpu, select_backend

    ok, detail = probe_tpu(
        timeout_s=float(os.environ.get("LOCUST_OPP_PROBE_S", 90)), retries=1
    )
    if not ok:
        print(f"[opp] tunnel down: {detail}", file=sys.stderr)
        return False
    select_backend("tpu", probe_timeout_s=120, retries=1)
    import jax

    print(f"[opp] on {jax.devices()[0].device_kind}", file=sys.stderr)
    return True


def phase_profile(rows_ab, corpus_bytes, sort_mode: str,
                  block_lines: int, caps=None, table_size=None) -> None:
    """jax.profiler device capture at the winning headline configuration
    (VERDICT r4 next #4): utilization computed from MEASURED device time
    instead of the analytic traffic model timing itself against
    tunnel-inflated wall clock.

    Records a ``profiled_roofline`` row — measured sort-family device
    ms, the model's estimated sort bytes, the measured utilization they
    imply, the device plane's top ops, and the xplane path (farm_loop
    commits ``artifacts/profiles`` alongside the ledger) — AND, through
    the obs attribution path (locust_tpu.obs.attribution, the family
    pairing's one home), a ``stage_device_time`` row with the xplane
    sort/scatter/dot families joined onto the Process stage.  Both rows
    are recorded with ``force=True``: CPU-fallback runs leave
    ``backend: "cpu"`` rows (every TPU-evidence reader filters on
    backend), TPU windows land the real thing — no extra sweep phases.
    """
    import bench
    import jax

    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.obs import attribution
    from locust_tpu.utils import artifacts, roofline

    row = {"sort_mode": sort_mode, "block_lines": block_lines, "caps": caps,
           "table_size": table_size,
           "corpus_mb": round(corpus_bytes / 1e6, 1)}
    try:
        eng = get_engine(
            bench.bench_engine_config(block_lines, table_size=table_size,
                                      sort_mode=sort_mode, **(caps or {}))
        )
        blocks = eng.prepare_blocks(rows_ab)
        blocks.block_until_ready()
        eng.run_blocks(blocks)  # compile + warm OUTSIDE the trace
        # Backend stamped into the capture name: a CPU-origin xplane
        # committed as TPU evidence contaminated artifacts/profiles once
        # (VERDICT r5 weak #1) — the filename now says what ran, and the
        # gz below is written for REAL device captures only.
        backend = jax.default_backend()
        row["capture_backend"] = backend
        prof_dir = os.path.join(
            artifacts.artifacts_dir(), "profiles",
            f"{int(time.time())}_{backend}_{sort_mode}_{block_lines}",
        )
        t0 = time.perf_counter()
        res, summary, xplane, join = attribution.attributed_run(
            lambda: eng.run_blocks(blocks), prof_dir, sort_mode
        )
        row["wall_s"] = round(time.perf_counter() - t0, 3)
        row["device_plane"] = summary.get("device_plane")
        row["device_total_ms"] = summary.get("device_total_ms")
        row["sort_device_ms"] = summary.get("sort_ms")
        row["scatter_device_ms"] = summary.get("scatter_ms")
        row["dot_device_ms"] = summary.get("dot_ms")
        if summary.get("error"):
            row["error"] = summary["error"]
        plane = (summary.get("planes") or {}).get(row.get("device_plane"))
        if plane:
            row["top_ops"] = plane["top_ops"]
        if xplane and backend == "tpu":
            # Commit ONE compressed file, not the raw capture tree —
            # xplane.pb is multi-MB and compresses ~10x.
            import gzip
            import shutil

            gz = os.path.join(
                os.path.dirname(prof_dir),
                os.path.basename(prof_dir) + ".xplane.pb.gz",
            )
            with open(xplane, "rb") as src, gzip.open(gz, "wb") as dst:
                shutil.copyfileobj(src, dst)
            shutil.rmtree(prof_dir, ignore_errors=True)
            row["xplane"] = os.path.relpath(gz, REPO)
            row["xplane_bytes"] = os.path.getsize(gz)
        elif xplane:
            # Off-TPU captures are parse smoke, not hardware evidence:
            # keep the reduced numbers in the row, drop the blob so it
            # can never be mistaken for the promised TPU capture
            # (VERDICT r5 weak #1 / next #2).
            import shutil

            shutil.rmtree(prof_dir, ignore_errors=True)
            row["xplane_skipped"] = f"non-TPU backend ({backend})"
        n_blocks = -(-rows_ab.shape[0] // block_lines)
        model = roofline.pipeline_sort_traffic(
            sort_mode, eng.cfg.key_lanes, eng.cfg.emits_per_block,
            eng.cfg.resolved_table_size, n_blocks,
            block_lines=eng.cfg.block_lines,
            line_width=eng.cfg.line_width,
        )
        row["est_sort_traffic_bytes"] = model["est_sort_traffic_bytes"]
        peak = roofline.PEAK_HBM_GB_S.get(jax.devices()[0].device_kind)
        # Family pairing (sort modes = sort HLOs; hasht adds scatters;
        # hasht-mxu adds the one-hot dots so one-hot bytes never pair
        # with a dot-free time — review finding, r6) now lives in ONE
        # place: locust_tpu.obs.attribution.family_join.
        sort_ms = None
        if "error" not in join:
            row["process_family"] = join["process_family"]
            sort_ms = join["process_device_ms"]
        if sort_ms and peak:
            # The model is an upper bound on traffic; this quotient is
            # therefore an upper bound on utilization FROM MEASURED TIME
            # — the honest pairing is (measured ms, modeled bytes) with
            # both fields in the row so the claim is auditable.
            ach = model["est_sort_traffic_bytes"] / 1e9 / (sort_ms / 1e3)
            row["measured_sort_gb_s"] = round(ach, 2)
            row["measured_hbm_utilization_pct"] = round(100 * ach / peak, 2)
        # The attribution evidence row (VERDICT r5 next #3 plumbing):
        # xplane families joined onto the Process stage, same capture.
        attribution.record_stage_device_row(
            join,
            {"sort_mode": sort_mode, "block_lines": block_lines,
             "table_size": table_size, "caps": caps,
             "corpus_mb": row["corpus_mb"],
             "capture_backend": row.get("capture_backend")},
            force=True,
        )
    except Exception as e:  # noqa: BLE001 - evidence, never kills the sweep
        row["error"] = f"{type(e).__name__}: {e}"[:300]
    artifacts.record("profiled_roofline", row, force=True)
    print(f"[opp] profiled roofline: {row}", file=sys.stderr)


def _scan_stage_ms(stage_fn, perturb, extract, x, k_hi: int = 8):
    """Device time of one stage execution, measured INSIDE one dispatch.

    Runs the stage ``k`` times in a single jit via ``lax.scan`` whose
    carry feeds a tiny data perturbation into each iteration (so XLA
    cannot hoist the loop-invariant body), for k=1 and k=k_hi; the
    per-iteration device time is the slope ``(wall(k_hi) - wall(1)) /
    (k_hi - 1)`` — dispatch/tunnel overhead is identical on both sides
    and cancels.  Returns ``(device_ms, oneshot_wall_ms)``.
    """
    import jax
    import jax.numpy as jnp

    def run_k(k: int) -> float:
        # The stage input MUST flow through the jit argument (not a
        # Python closure): a closure-captured array is a compile-time
        # constant and XLA will happily constant-fold the entire stage
        # (observed: map "measured" at 0.0 ms on the first CPU smoke).
        def f_impl(xx):
            def body(c, _):
                out = stage_fn(perturb(xx, c))
                return extract(out), None

            return jax.lax.scan(body, jnp.uint32(0), None, length=k)[0]

        f = jax.jit(f_impl)
        f(x).block_until_ready()  # compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            f(x).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    w1 = run_k(1)
    wk = run_k(k_hi)
    return max(0.0, (wk - w1) / (k_hi - 1)) * 1e3, w1 * 1e3


def phase_stage_device_time() -> None:
    """Decompose stage latency at the reference's 4,463-line shape into
    device compute vs dispatch/tunnel overhead (VERDICT r4 next #5).

    The committed ``stage_parity`` rows LOSE to the GTX 1060 on wall
    clock (1,729 ms vs ~82.7 ms at 4,463 lines) with the loss attributed
    — but never demonstrated — to axon-tunnel dispatch RTT.  This phase
    measures both sides of that claim:

      * ``rtt_ms``: median wall of a trivial dispatch — the floor every
        stage dispatch pays through the tunnel;
      * per-stage device time via ``_scan_stage_ms`` (k executions in
        ONE dispatch; overhead cancels in the slope).

    Done-criterion (VERDICT): device-side Process at 4,463 lines vs the
    reference's 78.176 ms (README.md:82-88) — recorded in the row as
    ``beats_ref_process``.
    """
    import jax
    import jax.numpy as jnp

    from locust_tpu.config import EngineConfig, default_sort_mode
    from locust_tpu.core.kv import KVBatch
    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.utils import artifacts

    ham = "/root/reference/hamlet.txt"
    if not os.path.exists(ham):
        return
    lines = open(ham, "rb").read().splitlines()
    mode = default_sort_mode(jax.default_backend())
    # ONE block covers the corpus: each stage is a single dispatch.
    cfg = EngineConfig(block_lines=8192, sort_mode=mode)
    eng = MapReduceEngine(cfg)
    rows = eng.rows_from_lines(lines)
    blk = jnp.asarray(next(iter(eng._blocks(rows))))

    # Dispatch RTT floor: trivial jitted op, median of 9 (compile first).
    bump = jax.jit(lambda x: x + 1.0)
    tiny = jnp.zeros((8,), jnp.float32)
    bump(tiny).block_until_ready()
    rtts = []
    for _ in range(9):
        t0 = time.perf_counter()
        bump(tiny).block_until_ready()
        rtts.append((time.perf_counter() - t0) * 1e3)
    rtt_ms = sorted(rtts)[len(rtts) // 2]

    # Stage inputs (each stage measured on its true predecessor output).
    kv = jax.block_until_ready(eng._map(blk)[0])
    skv = jax.block_until_ready(eng._process(kv))

    def perturb_rows(x, c):
        return x.at[0, 0].add((c & jnp.uint32(1)).astype(jnp.uint8))

    def perturb_vals(b, c):
        return KVBatch(
            b.key_lanes,
            b.values.at[0].add((c & jnp.uint32(1)).astype(jnp.int32)),
            b.valid,
        )

    def csum_batch(b):
        # Fold EVERY output field into the carry: an extract that reads
        # only ``values`` lets XLA dead-code the key-lane half of the
        # stage (payload operands are carried independently), silently
        # under-measuring it.
        return (
            b.values.astype(jnp.uint32).sum()
            + b.key_lanes.sum()
            + b.valid.astype(jnp.uint32).sum()
        ) & jnp.uint32(1)

    row = {"lines": len(lines), "sort_mode": mode,
           "block_lines": cfg.block_lines,
           "rtt_ms": round(rtt_ms, 2), "rtt_n": len(rtts),
           "ref_gpu_ms": [0.040, 78.176, 4.459]}
    try:
        m_dev, m_1 = _scan_stage_ms(
            lambda b: eng._map(b)[0], perturb_rows, csum_batch, blk,
        )
        p_dev, p_1 = _scan_stage_ms(
            eng._process, perturb_vals, csum_batch, kv
        )
        r_dev, r_1 = _scan_stage_ms(
            eng._reduce, perturb_vals, csum_batch, skv
        )
        row.update(
            map_device_ms=round(m_dev, 3), map_oneshot_ms=round(m_1, 1),
            process_device_ms=round(p_dev, 3),
            process_oneshot_ms=round(p_1, 1),
            reduce_device_ms=round(r_dev, 3),
            reduce_oneshot_ms=round(r_1, 1),
            beats_ref_process=bool(p_dev < 78.176),
        )
    except Exception as e:  # noqa: BLE001 - record what was measured
        row["error"] = f"{type(e).__name__}: {e}"[:300]
    artifacts.record("stage_device_time", row)
    print(f"[opp] stage device time: {row}", file=sys.stderr)


def phase_stage_parity() -> None:
    """Per-stage timing at the reference's own benchmark shapes (700 and
    4,463 hamlet lines, reference README.md:72-88) — the direct stage-table
    comparison against its GTX 1060 numbers."""
    from locust_tpu.config import EngineConfig
    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.utils import artifacts

    ham = "/root/reference/hamlet.txt"
    if not os.path.exists(ham):
        return
    all_lines = open(ham, "rb").read().splitlines()
    for n_lines in (700, len(all_lines)):
        eng = MapReduceEngine(EngineConfig(block_lines=1024))
        rows = eng.rows_from_lines(all_lines[:n_lines])
        eng.timed_run(rows)  # compile + warm
        best = None
        for _ in range(3):
            r = eng.timed_run(rows)
            if best is None or r.times.total_ms < best.times.total_ms:
                best = r
        row = {
            "lines": n_lines,
            "map_ms": round(best.times.map_ms, 3),
            "process_ms": round(best.times.process_ms, 3),
            "reduce_ms": round(best.times.reduce_ms, 3),
            "total_ms": round(best.times.total_ms, 3),
            "distinct": best.num_segments,
            "ref_gpu_ms": {"700": [0.047, 27.646, 1.712],
                           "4463": [0.040, 78.176, 4.459]}.get(str(n_lines)),
        }
        artifacts.record("stage_parity", row)
        print(f"[opp] stage parity {n_lines} lines: {row}", file=sys.stderr)


def _staged_rows():
    """One host-side corpus conversion feeding phases 3 - 3.7 (identical
    line_width): rows_from_lines over a 32MB corpus costs seconds of
    tunnel-window time per call.

    Also measures the corpus's lossless caps ONCE — the A/B phases run at
    the same auto-sized key_width/emits_per_line the headline bench will
    use (bench.py auto-sizes), so the winners bench.py adopts were
    measured at the configuration it actually runs.
    """
    import bench

    from locust_tpu.config import EngineConfig
    from locust_tpu.engine import MapReduceEngine

    lines = bench.load_corpus(int(os.environ.get("LOCUST_OPP_AB_BYTES", 32 << 20)))
    corpus_bytes = sum(len(ln) + 1 for ln in lines)
    kw, epl = bench.bench_auto_caps(lines, label="[opp]")
    rows = MapReduceEngine(EngineConfig(block_lines=32768)).rows_from_lines(lines)
    return rows, corpus_bytes, kw, epl


def _session_floor() -> float:
    """Rows at/after this ts count as THIS session's evidence (farm loop
    stamps LOCUST_SESSION_TS; manual runs fall back to 24h)."""
    try:
        session_ts = float(os.environ.get("LOCUST_SESSION_TS", 0) or 0)
    except (TypeError, ValueError):
        session_ts = 0.0
    return max(session_ts, time.time() - 24 * 3600)


def _session_row_ok(r: dict) -> bool:
    """Is this ledger row reusable evidence for the CURRENT session?

    Primary key: the measurement-code fingerprint — a row stamped with
    the current ``code`` was produced by the same compute path, so its
    numbers are commensurable with anything this session measures (and a
    row from a DIFFERENT fingerprint must re-run even if minutes old:
    carrying it would hand bench's evidence tuning a comparison across
    two code versions).  The row's ``jax`` version must also match this
    process's — an XLA upgrade changes codegen without touching our
    code.  Legacy rows without the code stamp fall back to the
    session-ts floor.  Everything is additionally bounded to 24h — a
    same-code row from last week shouldn't silently stand in for a
    window that could re-anchor it.  The ONE validity rule for every
    already-answered skip (variants, battery, engine-mode carry); both
    sweep entry points import it from here."""
    from locust_tpu.utils.artifacts import code_fingerprint

    try:
        ts = float(r.get("ts") or 0)
    except (TypeError, ValueError):
        return False
    if ts < time.time() - 24 * 3600:
        return False
    try:
        import jax

        if r.get("jax") not in (None, jax.__version__):
            return False
    except Exception:  # pragma: no cover - jax import must not gate reads
        pass
    code = r.get("code")
    if code is not None:
        return code == code_fingerprint()
    return ts >= _session_floor()


def _prior_mode_results(corpus_mb: float, caps) -> dict:
    """Session-fresh MEASURED sort-mode results at exactly this corpus
    shape and caps, unioned across ledger rows.  A window that died
    after hasht's compile must not make the next window re-pay it —
    mode-level resume, same idea as the variant-letter resume in
    tpu_opportunistic.  Only sides with an ``mb_s`` carry (errored modes
    re-run); shape and caps must match so an 8MB second-source row can
    never masquerade as headline-shape evidence."""
    from locust_tpu.utils.artifacts import ledger_rows

    out: dict = {}
    for r in ledger_rows():
        if (r.get("kind") != "engine_sort_mode_ab"
                or r.get("backend") != "tpu"):
            continue
        if not _session_row_ok(r):
            continue
        if r.get("corpus_mb") != corpus_mb or r.get("caps") != caps:
            continue
        try:
            row_ts = float(r.get("ts") or 0)
        except (TypeError, ValueError):
            continue
        for m, res in (r.get("modes") or {}).items():
            # Only FIRST-HAND measurements carry: a side that was itself
            # carried (tagged below) must not chain — re-recording a
            # carried number under a fresh ts would otherwise renew its
            # 24h validity forever, laundering a never-re-measured
            # result past the re-anchor bound.  Duplicates resolve by
            # NEWEST source ts, not file order: the ledger is
            # multi-writer and git-merged, so line order is meaningless.
            if (isinstance(res, dict) and "mb_s" in res
                    and "carried_from" not in res
                    and row_ts >= out.get(m, {}).get("carried_from", 0.0)):
                out[m] = {**res, "carried_from": row_ts}
    return out


def phase_fused_ab(rows_ab, corpus_bytes, caps=None) -> str:
    """First-window-slot fused verdict: engine-level fused vs hasht vs
    hasht-mxu rows BEFORE any other phase (variant compiles, bitonic
    anything) can eat the window.  Ordinary ``engine_sort_mode_ab`` rows
    — _prior_mode_results carries them into phase 3, so nothing is
    measured twice; bench's evidence tuning reads them the moment they
    land."""
    return phase_sort_mode_ab(rows_ab, corpus_bytes, caps=caps,
                              modes=FUSED_AB_MODES)


def phase_fused_stream_ab(rows_ab, corpus_bytes, caps=None) -> None:
    """Second-window-slot streaming verdict (megakernel v2): the
    persistent streaming kernel (``sort_mode="fused"`` through
    ``run_stream`` — the table stays VMEM-resident across a whole
    segment of blocks, settled once per segment) vs plain hasht
    streaming over the SAME block stream.  Ordinary
    ``engine_sort_mode_ab`` rows under the ``fused_stream`` /
    ``hasht_stream`` mode labels, so ``_prior_mode_results`` resumes a
    window that died after one side — nothing is measured twice and the
    row shape every evidence reader already parses carries the
    streaming numbers too.  Block count is bounded
    (``LOCUST_OPP_STREAM_AB_BLOCKS``): per-block dispatch rides the
    tunnel and a full 32MB stream must not eat the window."""
    import bench

    from locust_tpu.utils import artifacts

    corpus_mb = round(corpus_bytes / 1e6, 1)
    results = {
        m: r for m, r in _prior_mode_results(corpus_mb, caps).items()
        if m in FUSED_STREAM_AB_MODES
    }
    if results:
        print(f"[opp] fused-stream A/B resuming; already measured this "
              f"session: {sorted(results)}", file=sys.stderr)
    max_blocks = int(os.environ.get("LOCUST_OPP_STREAM_AB_BLOCKS", 24))
    for label in FUSED_STREAM_AB_MODES:
        if label in results:
            continue
        sort_mode = "fused" if label == "fused_stream" else "hasht"
        try:
            eng = get_engine(
                bench.bench_engine_config(32768, sort_mode=sort_mode,
                                          **(caps or {}))
            )
            bl = eng.cfg.block_lines
            n = min(rows_ab.shape[0], max_blocks * bl)
            streamed_bytes = corpus_bytes * n / max(1, rows_ab.shape[0])

            def blocks():
                for i in range(0, n, bl):
                    yield rows_ab[i:i + bl]

            t0 = time.perf_counter()
            res = eng.run_stream(blocks())  # compile + warm
            compile_s = time.perf_counter() - t0
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                res = eng.run_stream(blocks())
                best = min(best, time.perf_counter() - t0)
            results[label] = {
                "mb_s": round(streamed_bytes / 1e6 / best, 2),
                "best_s": round(best, 4),
                "compile_s": round(compile_s, 1),
                "blocks": -(-n // bl),
                "distinct": res.num_segments,
                "overflow_tokens": res.overflow_tokens,
                # Which formulation actually ran — "stream" is the
                # claim under test; None + demoted=True means the gate
                # turned the kernel off and this side IS hasht.
                "formulation": res.fused_kernel,
                "fused_demoted": bool(res.fused_demoted),
            }
        except Exception as e:  # noqa: BLE001 - one side must not cost the
            # window the other side's row; an errored side has no mb_s
            # and is re-attempted next window.
            results[label] = {"error": f"{type(e).__name__}: {e}"[:300]}
        print(f"[opp] mode={label}: {results[label]}", file=sys.stderr)
        artifacts.record(
            "engine_sort_mode_ab",
            {"corpus_mb": corpus_mb, "caps": caps,
             "modes": dict(results),
             "partial": any(
                 m not in results for m in FUSED_STREAM_AB_MODES
             )},
        )


def phase_sort_mode_ab(rows_ab, corpus_bytes, caps=None, modes=None) -> str:
    """Engine end-to-end per sort mode at bench shapes.

    Returns the winning mode so phase_block_lines sweeps AT that mode —
    bench.py only adopts a (sort_mode, block_lines) pair a window
    actually measured together.  ``modes`` restricts the sweep (the
    fused_ab first-slot phase); default is the full AB_SORT_MODES
    priority ladder.
    """
    import bench

    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.utils import artifacts

    modes = AB_SORT_MODES if modes is None else modes
    corpus_mb = round(corpus_bytes / 1e6, 1)
    results = {
        m: r for m, r in _prior_mode_results(corpus_mb, caps).items()
        if m in modes
    }
    if results:
        print(f"[opp] sort-mode A/B resuming; already measured this "
              f"session: {sorted(results)}", file=sys.stderr)
    for mode in modes:
        if mode in results:
            continue
        try:
            eng = get_engine(
                bench.bench_engine_config(32768, sort_mode=mode, **(caps or {}))
            )
            blocks = eng.prepare_blocks(rows_ab)
            blocks.block_until_ready()
            t0 = time.perf_counter()
            eng.run_blocks(blocks)  # compile + warm
            compile_s = time.perf_counter() - t0
            best = float("inf")
            for _ in range(3):
                res = eng.run_blocks(blocks)
                best = min(best, res.times.total_ms / 1e3)
            import jax

            from locust_tpu.utils import roofline

            n_blocks = -(-rows_ab.shape[0] // 32768)
            roof = roofline.summarize(
                mode, eng.cfg.key_lanes, eng.cfg.emits_per_block,
                eng.cfg.resolved_table_size, n_blocks, best,
                jax.devices()[0].device_kind,
                block_lines=eng.cfg.block_lines,
                line_width=eng.cfg.line_width,
            )
            results[mode] = {
                "mb_s": round(corpus_bytes / 1e6 / best, 2),
                "best_s": round(best, 4),
                "compile_s": round(compile_s, 1),
                "distinct": res.num_segments,
                # Loss signal for bench's evidence tuning: a side with
                # dropped tokens or missing distinct keys is never
                # adopted (bench._evidence_tuned_tpu_defaults).
                "overflow_tokens": res.overflow_tokens,
                "sort_gb_s": roof["achieved_sort_gb_s"],
                "hbm_utilization_pct": roof["hbm_utilization_pct"],
            }
        except Exception as e:  # noqa: BLE001 - one mode must not kill the
            # phase: bitonic runs first and a Mosaic reject there would
            # otherwise cost the window every OTHER mode's row.  An
            # errored side has no mb_s and can never be adopted.
            results[mode] = {"error": f"{type(e).__name__}: {e}"[:300]}
        print(f"[opp] mode={mode}: {results[mode]}", file=sys.stderr)
        # Record after EVERY mode: a window that closes mid-phase keeps
        # what it measured (bench's evidence tuning reads the latest row;
        # a partial row steers with the modes it has, under the same
        # joint caps rule).
        artifacts.record(
            "engine_sort_mode_ab",
            {"corpus_mb": corpus_mb, "caps": caps,
             "modes": dict(results),
             "partial": any(m not in results for m in AB_SORT_MODES)},
        )
    # The restricted (fused_ab) sweep must not hand downstream phases a
    # winner the FULL ladder never saw losing: its caller only wants the
    # rows landed early, so the winner is informational there too.
    winner = max(results, key=lambda m: results[m].get("mb_s", -1.0))
    if "mb_s" not in results[winner]:
        # EVERY mode errored (tunnel died mid-phase, or worse): hand the
        # downstream phases a known-good mode instead of re-raising the
        # same failure through their unguarded sweeps.
        print("[opp] all sort modes errored; downstream phases sweep at "
              "'hashp'", file=sys.stderr)
        return "hashp"
    return winner


def phase_block_lines(rows_ab, corpus_bytes, sort_mode: str = "hash",
                      caps=None):
    """block_lines tuning at the headline-bench shape — dispatch granularity
    vs per-block sort size is the one free knob left.  Swept at
    ``sort_mode`` (the phase-3 winner) and the row records it, so the
    (sort_mode, block_lines) pair bench.py adopts was measured jointly.

    Returns ``(winning block_lines, its staged device blocks)`` so
    phase_pallas_ab skips one full-corpus H2D; only the best-so-far
    staging is kept alive (losers — and failed sizes — are dropped as
    soon as they're decided, bounding peak HBM at ~2 stagings instead of
    all four)."""
    import bench

    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.utils import artifacts

    results = {}
    best_key, best_blocks = None, None
    # 16384 lost decisively in the committed r4 row (54.2 vs 64.0 MB/s at
    # 65536); the open question is now UPWARD — bigger blocks amortize
    # dispatch latency (large over the axon tunnel) and per-block fixed
    # costs, at the price of a bigger per-block sort.  781k bench lines
    # still fill >=3 blocks at 262144, so padding waste stays honest.
    sizes = (32768, 65536, 131072, 262144)
    for bl in sizes:
        try:
            eng = get_engine(
                bench.bench_engine_config(bl, sort_mode=sort_mode,
                                          **(caps or {}))
            )
            blocks = eng.prepare_blocks(rows_ab)
            blocks.block_until_ready()
            eng.run_blocks(blocks)  # compile + warm
            best = float("inf")
            for _ in range(3):
                res = eng.run_blocks(blocks)
                best = min(best, res.times.total_ms / 1e3)
            results[str(bl)] = {
                "mb_s": round(corpus_bytes / 1e6 / best, 2),
                "best_s": round(best, 4),
                # Loss signals so bench's lossless_sides filter can
                # actually reject a lossy block size (a bigger block
                # scales resolved_table_size and can truncate distinct).
                "distinct": res.num_segments,
                "overflow_tokens": res.overflow_tokens,
            }
        except Exception as e:  # noqa: BLE001 - the 131072/262144 sizes have
            # never run on hardware; an OOM/compile failure there must not
            # discard the measured sizes or kill the later phases (an
            # errored side has no mb_s and can never be adopted).
            results[str(bl)] = {"error": f"{type(e).__name__}: {e}"[:300]}
            blocks = None  # drop the failed size's staging before the next
        print(f"[opp] block_lines={bl}: {results[str(bl)]}", file=sys.stderr)
        if "mb_s" in results[str(bl)] and (
            best_key is None
            or results[str(bl)]["mb_s"] > results[best_key]["mb_s"]
        ):
            best_key, best_blocks = str(bl), blocks
        elif "mb_s" in results[str(bl)]:
            del blocks  # loser's staging: free its HBM before the next
        # Record after EVERY size: a window that closes mid-phase keeps
        # what it measured (same incremental rule as phase_sort_mode_ab).
        artifacts.record(
            "block_lines_ab",
            {"corpus_mb": round(corpus_bytes / 1e6, 1), "sort_mode": sort_mode,
             "caps": caps, "blocks": dict(results),
             "partial": bl != sizes[-1]},
        )
    if best_key is None:
        # Every size errored: hand downstream phases the static default
        # rather than crashing the remaining sweep.
        print("[opp] all block sizes errored; downstream phases run at "
              "32768", file=sys.stderr)
        return 32768, None
    return int(best_key), best_blocks


def phase_table_ab(rows_ab, corpus_bytes, sort_mode: str,
                   block_lines: int, caps=None, blocks=None):
    """Accumulator-size A/B at the winning (sort_mode, block_lines)
    (round-5 CPU finding transferred to TPU the evidence-tuned way):
    the fold re-aggregates every table row per block, and the default
    min(65536, emits_per_block) table is mostly padding at real
    vocabularies.  Sizes: the default, and the distinct-aware rule's
    choice (bench._auto_table_size) with one step below it.  The row
    records the measured distinct-token count; bench adopts only a
    jointly-measured (mode, block, table) chain, and only lossless
    sides (distinct/overflow recorded per side).

    Returns the winning table size (None = default, so downstream
    phases and tuning treat legacy behavior uniformly).
    """
    import bench

    from locust_tpu.io.loader import count_distinct_tokens
    from locust_tpu.utils import artifacts

    try:
        from locust_tpu.config import EngineConfig

        d = EngineConfig(block_lines=block_lines)
        # rows_ab are padded device rows; count on the host lines the
        # corpus loader produced (cheap: dedup first).
        lines = bench.load_corpus(
            int(os.environ.get("LOCUST_OPP_AB_BYTES", 32 << 20))
        )
        distinct = count_distinct_tokens([ln[: d.line_width] for ln in lines])
        auto = bench._auto_table_size(distinct, d.resolved_table_size)
        sizes = [d.resolved_table_size]
        if auto < d.resolved_table_size:
            sizes.append(auto)
            if auto // 2 >= max(4096, distinct):
                sizes.append(auto // 2)
    except Exception as e:  # noqa: BLE001 - phase must not kill the sweep
        artifacts.record("engine_table_ab",
                         {"error": f"{type(e).__name__}: {e}"[:300]})
        return None
    results = {}
    best_size, best_mb = None, -1.0
    for ts in sizes:
        try:
            eng = get_engine(
                bench.bench_engine_config(block_lines, table_size=ts,
                                          sort_mode=sort_mode,
                                          **(caps or {}))
            )
            if blocks is None:
                blocks = eng.prepare_blocks(rows_ab)
                blocks.block_until_ready()
            eng.run_blocks(blocks)  # compile + warm
            best, res = float("inf"), None
            for _ in range(3):
                res = eng.run_blocks(blocks)
                best = min(best, res.times.total_ms / 1e3)
            results[str(ts)] = {
                "mb_s": round(corpus_bytes / 1e6 / best, 2),
                "best_s": round(best, 4),
                "distinct": res.num_segments,
                "overflow_tokens": res.overflow_tokens,
                "truncated": res.truncated,
            }
            if not res.truncated and results[str(ts)]["mb_s"] > best_mb:
                best_mb, best_size = results[str(ts)]["mb_s"], ts
        except Exception as e:  # noqa: BLE001 - record, keep sweeping
            results[str(ts)] = {"error": f"{type(e).__name__}: {e}"[:300]}
        print(f"[opp] table_size={ts}: {results[str(ts)]}", file=sys.stderr)
        artifacts.record(
            "engine_table_ab",
            {"corpus_mb": round(corpus_bytes / 1e6, 1), "caps": caps,
             "sort_mode": sort_mode, "block_lines": block_lines,
             "measured_distinct": distinct, "tables": dict(results),
             "partial": ts != sizes[-1]},
        )
    if best_size == sizes[0]:
        return None  # default won; no override to carry forward
    return best_size


def phase_pallas_ab(rows_ab, corpus_bytes, sort_mode: str = "hash",
                    block_lines: int = 32768, caps=None,
                    blocks=None, table_size=None) -> None:
    """Engine end-to-end with the Pallas vs jnp Map tokenizer at the
    winning (sort_mode, block_lines) configuration — the joint
    measurement that can justify flipping the use_pallas default
    (VERDICT r2 weak #2: the flag has never been backed by engine-level
    hardware numbers).  The row records both fields so bench.py adopts
    the flag only on top of the exact configuration it was measured
    with.  Each side is isolated so a Pallas lowering failure records an
    error instead of killing the remaining phases.
    """
    import bench

    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.utils import artifacts

    results = {}
    for flag in (False, True):
        try:
            eng = get_engine(
                bench.bench_engine_config(block_lines, table_size=table_size,
                                          sort_mode=sort_mode,
                                          use_pallas=flag, **(caps or {}))
            )
            if blocks is None:
                blocks = eng.prepare_blocks(rows_ab)
                blocks.block_until_ready()
            eng.run_blocks(blocks)  # compile + warm
            best, res = float("inf"), None
            for _ in range(3):
                res = eng.run_blocks(blocks)
                best = min(best, res.times.total_ms / 1e3)
            results[str(flag)] = {
                "mb_s": round(corpus_bytes / 1e6 / best, 2),
                "best_s": round(best, 4),
                "distinct": res.num_segments,
                "overflow_tokens": res.overflow_tokens,
            }
        except Exception as e:  # noqa: BLE001 - record, don't kill the sweep
            results[str(flag)] = {"error": f"{type(e).__name__}: {e}"[:200]}
        print(f"[opp] use_pallas={flag}: {results[str(flag)]}",
              file=sys.stderr)
    artifacts.record(
        "engine_pallas_ab",
        {"corpus_mb": round(corpus_bytes / 1e6, 1), "sort_mode": sort_mode,
         "block_lines": block_lines, "table_size": table_size,
         "caps": caps, "pallas": results},
    )


def phase_stage_breakdown(rows_ab, corpus_bytes, sort_mode: str,
                          block_lines: int, caps=None,
                          table_size=None) -> None:
    """Per-stage timing at the WINNING headline configuration.

    stage_parity (below) reports the reference's own shapes (700/4463
    lines at block_lines=1024) for the direct GTX-1060 table comparison;
    this row instead answers "where does the remaining time go at the
    shape the headline bench actually runs" — the number that steers the
    next optimization (sort kernel vs map vs reduce).  Stage boundaries
    sync (timed_run), so total_ms here OVERSTATES the fused pipeline; the
    fused number at this exact configuration lives in the same window's
    block_lines_ab row (same corpus, same caps) — compare against that,
    not against this row's total.
    """
    import bench

    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.utils import artifacts

    try:
        eng = get_engine(
            bench.bench_engine_config(block_lines, table_size=table_size,
                                      sort_mode=sort_mode, **(caps or {}))
        )
        eng.timed_run(rows_ab)  # compile + warm
        best = None
        for _ in range(3):
            r = eng.timed_run(rows_ab)
            if best is None or r.times.total_ms < best.times.total_ms:
                best = r
        row = {
            "corpus_mb": round(corpus_bytes / 1e6, 1),
            "sort_mode": sort_mode,
            "block_lines": block_lines,
            "table_size": table_size,
            "caps": caps,
            "map_ms": round(best.times.map_ms, 1),
            "process_ms": round(best.times.process_ms, 1),
            "reduce_ms": round(best.times.reduce_ms, 1),
            "total_ms": round(best.times.total_ms, 1),
            "distinct": best.num_segments,
        }
        from locust_tpu.config import HASHT_FAMILY

        if sort_mode in HASHT_FAMILY:
            # timed_run splits stages via the grouping interface, which
            # for the hasht family is the stock hashp1 formulation — the
            # fused fold (the number that wins A/Bs) has no separable
            # Process/Reduce.
            row["note"] = "stages measured via hashp1-equivalent split"
    except Exception as e:  # noqa: BLE001 - informational phase: a failure
        # here must not kill stage_parity/emits/key-width/stream behind it
        row = {
            "corpus_mb": round(corpus_bytes / 1e6, 1),
            "sort_mode": sort_mode,
            "block_lines": block_lines,
            "error": f"{type(e).__name__}: {e}"[:300],
        }
    artifacts.record("stage_breakdown_bench_shape", row)
    print(f"[opp] bench-shape stage breakdown: {row}", file=sys.stderr)


def phase_emits_ab(rows_ab, corpus_bytes, key_width: int = 32) -> None:
    """emits_per_line A/B at the headline-bench shape.

    The reference hardcodes EMITS_PER_LINE=20 (main.cu:19); most slots are
    empty padding that the Process-stage sort still pays for.  A smaller
    cap shrinks the sorted array proportionally and is LOSSLESS whenever
    the overflow counter stays 0 (identical output table) — the row
    records overflow so a cap that drops tokens is self-evident.
    """
    import bench

    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.utils import artifacts

    results = {}
    # 17 = hamlet's max tokens/line (the lossless floor for the default
    # bench corpus); 10/12 are lossless only for the Zipf corpus and will
    # show nonzero overflow_tokens on hamlet — recorded either way.
    blocks = None  # staged once: prepare_blocks doesn't depend on the cap
    for epl in (10, 12, 17, 20):
        eng = get_engine(
            bench.bench_engine_config(32768, emits_per_line=epl,
                                      key_width=key_width)
        )
        if blocks is None:
            blocks = eng.prepare_blocks(rows_ab)
            blocks.block_until_ready()
        eng.run_blocks(blocks)  # compile + warm
        best, res = float("inf"), None
        for _ in range(3):
            res = eng.run_blocks(blocks)
            best = min(best, res.times.total_ms / 1e3)
        results[str(epl)] = {
            "mb_s": round(corpus_bytes / 1e6 / best, 2),
            "best_s": round(best, 4),
            "overflow_tokens": res.overflow_tokens,
            "distinct": res.num_segments,
        }
        print(f"[opp] emits_per_line={epl}: {results[str(epl)]}",
              file=sys.stderr)
    artifacts.record(
        "emits_per_line_ab",
        {"corpus_mb": round(corpus_bytes / 1e6, 1), "key_width": key_width,
         "emits": results},
    )


def phase_key_width_ab(rows_ab, corpus_bytes) -> None:
    """key_width A/B at the headline-bench shape.

    The reference caps keys at 30 bytes (KeyValue.h:15); our default
    rounds to 32 = 8 uint32 lanes.  Every sort mode carries (or gathers)
    all key lanes per row, so a corpus whose longest token fits 16 bytes
    halves that traffic at key_width=16 with ZERO semantic change —
    verified here by comparing the decoded host table against the
    32-byte-width run, not just the distinct count.  (hamlet max token:
    14 bytes; the Zipf generator's: 7.)
    """
    import bench

    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.utils import artifacts

    results = {}
    baseline_pairs = None
    blocks = None  # staged once: line blocks don't depend on key_width
    for kw in (32, 16):
        eng = get_engine(
            bench.bench_engine_config(32768, key_width=kw)
        )
        if blocks is None:
            blocks = eng.prepare_blocks(rows_ab)
            blocks.block_until_ready()
        eng.run_blocks(blocks)  # compile + warm
        best, res = float("inf"), None
        for _ in range(3):
            res = eng.run_blocks(blocks)
            best = min(best, res.times.total_ms / 1e3)
        pairs = res.to_host_pairs()
        if baseline_pairs is None:
            baseline_pairs = pairs
        results[str(kw)] = {
            "mb_s": round(corpus_bytes / 1e6 / best, 2),
            "best_s": round(best, 4),
            "distinct": res.num_segments,
            "table_exact_vs_32": pairs == baseline_pairs,
        }
        print(f"[opp] key_width={kw}: {results[str(kw)]}", file=sys.stderr)
    artifacts.record(
        "key_width_ab",
        {"corpus_mb": round(corpus_bytes / 1e6, 1), "widths": results},
    )


def phase_stream() -> None:
    """Optional ($LOCUST_OPP_STREAM_MB) big streaming corpus in bounded RSS.

    Caps are auto-sized with a bounded-memory measuring pass (the CLI's
    ``--stream --auto-caps`` machinery): the Zipf corpus's 7-byte tokens
    at <=10/line shrink the per-fold sort payload ~4x vs the default
    32-byte key slots, all host-verified lossless.
    """
    stream_mb = int(os.environ.get("LOCUST_OPP_STREAM_MB", 0))
    if not stream_mb:
        return
    import bench

    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.io.corpus import write_corpus
    from locust_tpu.io.loader import StreamingCorpus, measure_caps_stream, size_caps
    from locust_tpu.utils import artifacts

    from locust_tpu.config import EngineConfig

    path = f"/tmp/opp_stream_{stream_mb}.txt"
    if not os.path.exists(path):
        write_corpus(path, stream_mb * 1_000_000, n_vocab=50_000)
    size = os.path.getsize(path)
    d = EngineConfig()  # ceilings = the engine defaults, like every
    t0 = time.perf_counter()  # other auto-caps site
    measure_stream = StreamingCorpus(path, d.line_width, 32768)
    fp = measure_stream.fingerprint()
    max_tok, max_per_line = measure_caps_stream(measure_stream)
    kw, epl = size_caps(max_tok, max_per_line, d.key_width, d.emits_per_line)
    print(f"[opp] stream caps: max_token={max_tok}B max_tokens/line="
          f"{max_per_line} -> key_width={kw} emits_per_line={epl} "
          f"({time.perf_counter()-t0:.1f}s measure pass)", file=sys.stderr)
    eng = MapReduceEngine(
        bench.bench_engine_config(32768, key_width=kw, emits_per_line=epl)
    )
    run_stream_src = StreamingCorpus(path, d.line_width, 32768)
    if run_stream_src.fingerprint() != fp:
        # Same staleness rule as cli.py's --auto-caps: a corpus mutated
        # between the passes would make the measured caps lossy.
        print("[opp] stream: corpus changed between measure and run; "
              "skipping phase", file=sys.stderr)
        return
    t0 = time.perf_counter()
    res = eng.run_stream(run_stream_src)
    wall = time.perf_counter() - t0
    rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    row = {
        "corpus_mb": round(size / 1e6, 1),
        "wall_s": round(wall, 1),
        "mb_s": round(size / 1e6 / wall, 2),
        "caps": {"key_width": kw, "emits_per_line": epl},
        "distinct": res.num_segments,
        "truncated": res.truncated,
        "peak_rss_mb": round(rss_mb, 0),
    }
    artifacts.record("stream_scale", row)
    print(f"[opp] stream: {json.dumps(row)}", file=sys.stderr)


def _guard(name: str, fn, default=None):
    """Run one phase; on failure, log + re-probe the tunnel FRESH and
    either continue (tunnel alive: the failure was phase-local, e.g. a
    Mosaic 500) or raise (tunnel gone: every later phase would just burn
    minutes timing out).  The 07-31 18:55 window died with zero engine
    rows because one phase crash unwound the whole sweep."""
    try:
        return fn()
    except KeyboardInterrupt:
        raise
    except Exception as e:
        print(f"[opp] phase {name} FAILED: {type(e).__name__}: {e}",
              file=sys.stderr)
        from locust_tpu import backend as _b

        for marker in (_b._PROBE_OK_MARKER, _b._PROBE_FAIL_MARKER):
            try:
                os.unlink(marker)
            except OSError:
                pass
        ok, detail = _b.probe_tpu(timeout_s=60, retries=1)
        if not ok:
            raise RuntimeError(
                f"tunnel gone after phase {name}: {detail}"
            ) from e
        print(f"[opp] tunnel still up ({detail}); continuing past {name}",
              file=sys.stderr)
        return default


def run_phases(staged=None) -> None:
    """Phases 2.5 -> 4, decision-driving A/Bs FIRST: the engine sort-mode
    A/B (which steers the next driver bench via evidence tuning, and is
    the fused megakernel's + bitonic's engine-level verdict) must land
    before the informational stage-parity tables — a short window that
    closes mid-sweep should leave the rows that change behavior, not the
    ones that only describe it.  Each phase is guarded: a phase-local
    crash skips to the next phase on a known-live tunnel (fallback
    params are the committed evidence-tuned config) instead of
    abandoning the window.  ``staged`` lets the full-sweep entry point
    (tpu_opportunistic, which stages early for its first-slot fused_ab
    phase) hand over its staging instead of re-paying the 32MB host
    conversion."""
    if staged is None:
        staged = _guard("staging", _staged_rows)
    if staged is None:
        # Staging failed on a live tunnel (bad corpus override, loader
        # OOM): the row-dependent phases can't run, but these three take
        # no staged rows and can still leave evidence for the window.
        _guard("stage_device_time", phase_stage_device_time)
        _guard("stage_parity", phase_stage_parity)
        _guard("stream", phase_stream)
        return
    rows_ab, corpus_bytes, kw, epl = staged
    caps = {"key_width": kw, "emits_per_line": epl}
    winner = _guard(
        "sort_mode_ab",
        lambda: phase_sort_mode_ab(rows_ab, corpus_bytes, caps=caps),
        default_sort_mode("tpu"),
    )
    bl = _guard(
        "block_lines",
        lambda: phase_block_lines(rows_ab, corpus_bytes, sort_mode=winner,
                                  caps=caps),
        (65536, None),  # committed block A/B winner (block_lines_ab 07-31)
    )
    best_bl, best_blocks = bl
    best_ts = _guard(
        "table_ab",
        lambda: phase_table_ab(rows_ab, corpus_bytes, sort_mode=winner,
                               block_lines=best_bl, caps=caps,
                               blocks=best_blocks),
    )
    _guard(
        "pallas_ab",
        lambda: phase_pallas_ab(rows_ab, corpus_bytes, sort_mode=winner,
                                block_lines=best_bl, caps=caps,
                                blocks=best_blocks, table_size=best_ts),
    )
    # VERDICT r4 order: measured utilization (#4) and the device-vs-
    # tunnel decomposition (#5) before the informational tables.  The
    # decomposition runs FIRST: jax.profiler has never run against the
    # axon remote plugin, and an in-C hang there (unkillable in-process)
    # would otherwise cost the window every later phase — ordinary
    # compiles are the known-safe risk.
    _guard("stage_device_time", phase_stage_device_time)
    _guard(
        "profile",
        lambda: phase_profile(rows_ab, corpus_bytes, sort_mode=winner,
                              block_lines=best_bl, caps=caps,
                              table_size=best_ts),
    )
    _guard(
        "stage_breakdown",
        lambda: phase_stage_breakdown(rows_ab, corpus_bytes,
                                      sort_mode=winner,
                                      block_lines=best_bl, caps=caps,
                                      table_size=best_ts),
    )
    _guard("stage_parity", phase_stage_parity)
    _guard("emits_ab",
           lambda: phase_emits_ab(rows_ab, corpus_bytes, key_width=kw))
    _guard("key_width_ab",
           lambda: phase_key_width_ab(rows_ab, corpus_bytes))
    _guard("stream", phase_stream)


def main() -> int:
    if not tunnel_gate():
        return 3
    run_phases()
    print("[opp] resume sweep complete", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
