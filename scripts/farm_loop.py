"""Detached TPU-window farming loop (round 4+).

The axon tunnel flaps; evidence only accumulates while a window is open
(CLAUDE.md).  This loop probes on an interval and, whenever the tunnel is
up, captures in strict value order:

  1. a fresh headline bench (``python bench.py`` — evidence-tuned config,
     appends a ``kind: bench`` row) when stale: >1h since the last TPU
     bench row, or a config-driving A/B row postdates it; re-checked
     AFTER the sweep too, so a winner flipped mid-window re-anchors the
     headline before the tunnel can close
  2. the full decision sweep (``scripts/tpu_opportunistic.py``: unmeasured
     sort variants -> engine sort-mode/block/table/pallas A/Bs + stage
     decomposition/profiler/parity -> Pallas check battery last) —
     includes the hasht and bitonic kernel verdicts; session-answered
     phases are skipped so each window spends compiles on open questions
  3. the 512MB bounded-RSS streaming phase, once per session
  4. auto-commits ``artifacts/tpu_runs.jsonl`` (pathspec-only commit, so
     it cannot sweep up unrelated working-tree edits)

Yields to any already-running bench/sweep process (e.g. the driver's
end-of-round bench) and self-expires at the deadline so it can never
collide with the next round's loop.

Run detached:  nohup python scripts/farm_loop.py --hours 10 \
                   >> /tmp/locust_farm.log 2>&1 &
"""

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEDGER = os.path.join(REPO, "artifacts", "tpu_runs.jsonl")
PROFILES = os.path.join(REPO, "artifacts", "profiles")
# Session floor for the sweep's already-answered skips.  Defaults to
# farm start; an explicit LOCUST_SESSION_TS pins it across farm RESTARTS
# within one build session — otherwise every restart would orphan the
# evidence captured before it and the next window would re-pay those
# compiles (observed 07-31: an 18:43 window's 8 variant rows predated a
# 19:48 farm restart's stamp).
try:
    SESSION_TS = float(os.environ.get("LOCUST_SESSION_TS") or 0) or time.time()
except (TypeError, ValueError):
    SESSION_TS = time.time()

sys.path.insert(0, REPO)
# The one hardened ledger reader.  This import chain is jax-free
# (locust_tpu/__init__ and utils/__init__ are both lazy; artifacts.py
# imports no jax at module top) — this supervisor must STAY jax-free for
# its whole life, because a wedged axon tunnel hangs any process that
# touches a jax backend; probes/jobs run in killable subprocesses
# instead.  test_farm_loop_import_is_jax_free pins the invariant.
from locust_tpu.utils.artifacts import (  # noqa: E402
    CONFIG_AB_KINDS as _artifacts_CONFIG_AB_KINDS,
    latest_row_ts as _latest_row_ts,
    ledger_rows as _ledger_rows,
)


def ledger_rows() -> list[dict]:
    # Reads pinned to LEDGER — the same file commit_ledger() git-commits
    # — so a $LOCUST_ARTIFACTS_DIR override can't make the harvest
    # schedule and the committed evidence diverge.
    return _ledger_rows(LEDGER)


def latest_ts(kind: str, backend: str = "tpu") -> float:
    return _latest_row_ts(kind, backend, path=LEDGER)


def log(msg: str) -> None:
    print(f"[farm {time.strftime('%H:%M:%S')}] {msg}", flush=True)


JOB_SCRIPTS = ("bench.py", "tpu_opportunistic.py", "opp_resume.py")


def _python_procs_running(names, exclude_self=True):
    """PIDs of live python processes whose script basename is in ``names``."""
    me = os.getpid()
    hits = []
    for pid_dir in os.listdir("/proc"):
        if not pid_dir.isdigit() or (exclude_self and int(pid_dir) == me):
            continue
        try:
            with open(f"/proc/{pid_dir}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
        except OSError:
            continue
        if not argv or b"python" not in os.path.basename(argv[0]):
            continue
        if any(
            os.path.basename(a.decode(errors="replace")) in names
            for a in argv[1:3]
        ):
            hits.append(int(pid_dir))
    return hits


def other_jobs_running() -> bool:
    """True if a bench/sweep PYTHON process is live — the driver's
    end-of-round bench must win the window, not fight us.

    Reads /proc argv directly instead of ``pgrep -f``: a full-cmdline
    regex also matches unrelated processes that merely MENTION a script
    name somewhere in a long argument (observed: the driver harness's own
    command line), which would make this loop yield forever."""
    return bool(_python_procs_running(JOB_SCRIPTS))


def probe() -> bool:
    """Subprocess-isolated tunnel probe: a wedged tunnel hangs any python
    that touches a jax backend (CLAUDE.md), so the probe must be killable."""
    try:
        r = subprocess.run(  # locust: noqa[R006] the probe must see the ambient axon plugin — pinning the env away would probe nothing; timeout=150 bounds a wedged tunnel
            [sys.executable, "-c",
             "from locust_tpu.backend import probe_tpu;"
             "ok, d = probe_tpu(timeout_s=90, retries=1);"
             "import sys; sys.exit(0 if ok else 3)"],
            cwd=REPO, timeout=150, capture_output=True, text=True,
        )
        return r.returncode == 0
    except Exception:
        return False


def run(cmd: list[str], timeout: float, env: dict | None = None) -> int:
    log(f"run: {' '.join(cmd)} (timeout {timeout:.0f}s)")
    # Child writes pinned to the same ledger this loop READS and commits
    # (ADVICE r5): bench/sweep children append through artifacts_dir(),
    # which honors an inherited $LOCUST_ARTIFACTS_DIR — launched with
    # that set, they would land evidence elsewhere while bench_stale()
    # and the phase skips watch LEDGER, so every window would re-pay its
    # compiles and the commit loop would push nothing.
    env = dict(os.environ if env is None else env)
    env["LOCUST_ARTIFACTS_DIR"] = os.path.dirname(LEDGER)
    try:
        r = subprocess.run(
            cmd, cwd=REPO, timeout=timeout, env=env,
            stdout=subprocess.DEVNULL, stderr=sys.stderr,
        )
        log(f"rc={r.returncode}")
        return r.returncode
    except subprocess.TimeoutExpired:
        log("TIMEOUT")
        return 124
    except Exception as e:  # noqa: BLE001 - the loop must survive anything
        log(f"error: {type(e).__name__}: {e}")
        return 1


def commit_ledger() -> None:
    """Commit ONLY the evidence paths (ledger + COMPRESSED xplane
    captures); retry briefly on index-lock races with the interactive
    session's own commits.  Raw capture trees (a killed phase_profile
    leaves its multi-MB prof_dir behind — the gzip+cleanup only runs on
    success) are never staged: only *.xplane.pb.gz files that a
    committed-able ledger row actually CLAIMS (its ``xplane`` field) —
    an orphan gz with no row is exactly how a CPU-origin capture once
    landed as TPU evidence (VERDICT r5 weak #1), so orphans are left
    uncommitted for a human to inspect."""
    import glob

    ledgered = {
        os.path.basename(str(r.get("xplane")))
        for r in ledger_rows()
        if r.get("xplane")
    }
    paths = [LEDGER] + [
        p
        for p in sorted(glob.glob(os.path.join(PROFILES, "*.xplane.pb.gz")))
        if os.path.basename(p) in ledgered
    ]
    diff = subprocess.run(
        ["git", "status", "--porcelain", "--"] + paths,
        cwd=REPO, capture_output=True, text=True,
    )
    if not diff.stdout.strip():
        return  # tracked and unchanged, nothing new
    for _ in range(5):
        add = subprocess.run(["git", "add", "--"] + paths, cwd=REPO,
                             capture_output=True, text=True)
        c = subprocess.run(
            ["git", "commit", "-m",
             "Ledger: TPU window evidence rows (farm loop)", "--"] + paths,
            cwd=REPO, capture_output=True, text=True,
        )
        if c.returncode == 0:
            log(f"committed ledger: {c.stdout.strip().splitlines()[0]}")
            return
        if "lock" in (c.stderr + add.stderr).lower():
            time.sleep(3)
            continue
        log(f"commit skipped: {(c.stdout + c.stderr).strip()[:200]}")
        return


def next_ab_bytes() -> int:
    """Second-source the sort-mode A/B across corpus sizes (VERDICT r4
    next #9): the first complete post-hasht row anchors the 32MB
    headline shape; later windows re-run at 8MB then 64MB so the
    hashp2/hasht ordering is confirmed (or refuted) at different shapes
    instead of resting on one window's ~1% margin."""
    done_mb = set()
    for r in ledger_rows():
        if (
            r.get("kind") == "engine_sort_mode_ab"
            and r.get("backend") == "tpu"
            and isinstance(r.get("modes"), dict)
            # Only COMPLETE rows that measured hasht retire a size:
            # hasht runs FIRST in the A/B, so a window that dies after
            # one mode leaves a partial hasht-only row — treating that
            # as "answered" would skip the hashp2 comparison the row
            # exists for (code review, r5).  Older rows predate hasht's
            # priority slot and don't answer the question either way.
            and not r.get("partial")
            and isinstance(r["modes"].get("hasht"), dict)
            and "mb_s" in r["modes"]["hasht"]
        ):
            try:
                done_mb.add(round(float(r.get("corpus_mb") or 0)))
            except (TypeError, ValueError):
                continue  # multi-writer ledger: never crash the loop
    for mb, nbytes in ((34, 32 << 20), (8, 8 << 20), (67, 64 << 20)):
        if mb not in done_mb:
            return nbytes
    return 32 << 20


def bench_stale() -> bool:
    """Re-capture the headline when it is >1h old (doubles as a repeat
    measurement — every TPU number in the repo should be second-sourced)
    OR when a CONFIG-DRIVING A/B row postdates the last bench row:
    bench.py derives its configuration from exactly the
    ``CONFIG_AB_KINDS`` rows, so newer tuning inputs mean the committed
    headline no longer reflects the adopted config."""
    b = latest_ts("bench")
    if time.time() - b > 3600:
        return True
    return any(
        latest_ts(kind) > b for kind in _artifacts_CONFIG_AB_KINDS
    )


def harvest_window() -> None:
    """One open window: bench -> sweep -> re-anchor bench -> commit."""
    # 1. Headline bench through the driver's own path, when stale.
    if bench_stale():
        run([sys.executable, "bench.py"], timeout=1300)
        commit_ledger()
    # 2. Full decision sweep (hasht + bitonic verdicts, sort-mode/block/
    #    pallas A/Bs, profiler capture, stage device-time decomposition,
    #    Pallas check battery, stage parity, caps A/Bs).  The stream
    #    phase rides along until a stream_scale row has actually landed
    #    in the ledger — derived from the ledger each window, so a sweep
    #    that dies before the stream phase retries it next window.
    env = dict(os.environ)
    if not latest_ts("stream_scale"):
        env["LOCUST_OPP_STREAM_MB"] = os.environ.get(
            "LOCUST_FARM_STREAM_MB", "512")
    env["LOCUST_OPP_AB_BYTES"] = os.environ.get(
        "LOCUST_OPP_AB_BYTES", str(next_ab_bytes()))
    # Session scope for the sweep's "already answered" skips: only rows
    # produced after THIS farm loop started retire its phases 1-2.
    env["LOCUST_SESSION_TS"] = str(SESSION_TS)
    run([sys.executable, os.path.join("scripts", "tpu_opportunistic.py")],
        timeout=2400, env=env)
    commit_ledger()
    # 3. Re-anchor the headline IN THIS WINDOW if the sweep's A/B rows
    #    changed the tuning inputs: the flapping tunnel may never reopen
    #    (CLAUDE.md), so "next window" is not a safe place to capture
    #    the bench at a freshly-flipped config.
    if bench_stale():
        run([sys.executable, "bench.py"], timeout=1300)
        commit_ledger()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hours", type=float, default=10.0,
                    help="self-expire after this many hours")
    ap.add_argument("--interval", type=float, default=480.0,
                    help="seconds between probes")
    args = ap.parse_args()
    # Mutual exclusion: CLAUDE.md says start a loop every session, and a
    # session restart can leave the previous (self-expiring) loop alive —
    # two loops would harvest the same single-chip window concurrently
    # and pollute the decision A/B rows with contended timings.
    others = _python_procs_running(("farm_loop.py",))
    if others:
        log(f"another farm_loop is already running (pid {others[0]}); "
            "exiting — kill it first to replace the schedule")
        return 0
    deadline = time.time() + args.hours * 3600
    log(f"farming until {time.strftime('%H:%M:%S', time.localtime(deadline))} "
        f"(probe every {args.interval:.0f}s)")
    while time.time() < deadline:
        if other_jobs_running():
            log("yielding: bench/sweep already running")
        elif probe():
            log("tunnel UP — harvesting")
            harvest_window()
        else:
            log("tunnel down")
        time.sleep(max(10.0, min(args.interval, deadline - time.time())))
    log("deadline reached; exiting")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
