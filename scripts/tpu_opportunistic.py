"""Opportunistic TPU evidence sweep: run whatever fits a tunnel-up window.

The remote-TPU tunnel flaps (VERDICT r2 missing #1); this script is the
one-shot "the tunnel is up, capture everything" bundle.  Each phase appends
rows to ``artifacts/tpu_runs.jsonl`` via locust_tpu.utils.artifacts, so a
partial window still leaves committed evidence.  Phases, cheapest first:

  1. sort-variant bench at the engine's true Process-stage shape
     (B-G; A_lex9 is skipped — its XLA compile alone outlasts windows)
  2. the Pallas tokenizer check battery (scripts/tpu_checks.py inline)
  3. engine end-to-end A/B across sort modes at bench shapes
  4. (optional, $LOCUST_OPP_STREAM_MB) big-corpus streaming run in bounded
     RSS — the north-star-scale check that is throughput-infeasible on CPU

Exit codes: 0 = all requested phases captured, 3 = tunnel down, 1 = error.
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from locust_tpu.config import machine_cache_dir  # noqa: E402 - jax-free

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", machine_cache_dir())

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    import opp_resume

    if not opp_resume.tunnel_gate():
        return 3

    # Phase 1: sort variants at the engine shape (table + block emits).
    env = dict(os.environ)
    # Priority order (a short window should answer the open question
    # first): J = the hasht scatter primitive (VERDICT r4 next #2: is the
    # .at[].add serialized on TPU, the single biggest unknown on the
    # headline), K = the MXU-histogram backup for the same role, H = the
    # Pallas bitonic kernel, C = the payload-carry incumbent, then the
    # rest; radix (E/F) last — already measured losers (2.5-3x), only
    # re-timed if the window is generous.
    env["LOCUST_SORT_VARIANTS"] = "J,K,H,I,G,C,B,D,E,F"
    env["N"] = str(65536 + 32768 * 20)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_sort_variants.py"),
         "--backend", "tpu"],
        env=env, timeout=560, capture_output=True, text=True,
    )
    print(r.stdout, file=sys.stderr)
    if r.returncode != 0:
        print(f"[opp] sort variants failed: {r.stderr[-500:]}", file=sys.stderr)

    # Phase 2: Pallas check battery (separate process: own jit namespace).
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tpu_checks.py")],
        timeout=560, capture_output=True, text=True,
    )
    print(r.stdout, file=sys.stderr)
    if r.returncode != 0:
        print(f"[opp] tpu_checks failed: {r.stderr[-500:]}", file=sys.stderr)

    # Phases 2.5 -> 4 are shared with the window-resume entry point
    # (scripts/opp_resume.py) so the two sweeps can never diverge.
    opp_resume.run_phases()

    print("[opp] sweep complete", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
