"""Opportunistic TPU evidence sweep: run whatever fits a tunnel-up window.

The remote-TPU tunnel flaps (VERDICT r2 missing #1); this script is the
one-shot "the tunnel is up, capture everything" bundle.  Each phase appends
rows to ``artifacts/tpu_runs.jsonl`` via locust_tpu.utils.artifacts, so a
partial window still leaves committed evidence.  Phases, cheapest first:

  1. sort-variant bench at the engine's true Process-stage shape
     (B/C/D/E; A_lex9 is skipped — its XLA compile alone outlasts windows)
  2. the Pallas tokenizer check battery (scripts/tpu_checks.py inline)
  3. engine end-to-end A/B across sort modes at bench shapes
  4. (optional, $LOCUST_OPP_STREAM_MB) big-corpus streaming run in bounded
     RSS — the north-star-scale check that is throughput-infeasible on CPU

Exit codes: 0 = all requested phases captured, 3 = tunnel down, 1 = error.
"""

import json
import os
import resource
import subprocess
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_comp_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    from locust_tpu.backend import probe_tpu, select_backend

    ok, detail = probe_tpu(timeout_s=float(os.environ.get("LOCUST_OPP_PROBE_S", 90)),
                           retries=1)
    if not ok:
        print(f"[opp] tunnel down: {detail}", file=sys.stderr)
        return 3
    select_backend("tpu", probe_timeout_s=120, retries=1)

    import jax

    from locust_tpu.utils import artifacts

    print(f"[opp] on {jax.devices()[0].device_kind}; sweeping", file=sys.stderr)

    # Phase 1: sort variants at the engine shape (table + block emits).
    env = dict(os.environ)
    env["LOCUST_SORT_VARIANTS"] = "B,C,D,E,F"
    env["N"] = str(65536 + 32768 * 20)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_sort_variants.py"),
         "--backend", "tpu"],
        env=env, timeout=560, capture_output=True, text=True,
    )
    print(r.stdout, file=sys.stderr)
    if r.returncode != 0:
        print(f"[opp] sort variants failed: {r.stderr[-500:]}", file=sys.stderr)

    # Phase 2: Pallas check battery (separate process: own jit namespace).
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tpu_checks.py")],
        timeout=560, capture_output=True, text=True,
    )
    print(r.stdout, file=sys.stderr)
    if r.returncode != 0:
        print(f"[opp] tpu_checks failed: {r.stderr[-500:]}", file=sys.stderr)

    # Phase 2.5: per-stage timing at the REFERENCE's own benchmark shapes
    # (700 and 4,463 hamlet lines, reference README.md:72-88) — the direct
    # stage-table comparison against its GTX 1060 numbers.
    sys.path.insert(0, REPO)
    import bench

    from locust_tpu.config import EngineConfig
    from locust_tpu.engine import MapReduceEngine

    ham = "/root/reference/hamlet.txt"
    if os.path.exists(ham):
        all_lines = open(ham, "rb").read().splitlines()
        for n_lines in (700, len(all_lines)):
            eng = MapReduceEngine(EngineConfig(block_lines=1024))
            rows = eng.rows_from_lines(all_lines[:n_lines])
            eng.timed_run(rows)  # compile + warm
            best = None
            for _ in range(3):
                r = eng.timed_run(rows)
                if best is None or r.times.total_ms < best.times.total_ms:
                    best = r
            row = {
                "lines": n_lines,
                "map_ms": round(best.times.map_ms, 3),
                "process_ms": round(best.times.process_ms, 3),
                "reduce_ms": round(best.times.reduce_ms, 3),
                "total_ms": round(best.times.total_ms, 3),
                "distinct": best.num_segments,
                "ref_gpu_ms": {"700": [0.047, 27.646, 1.712],
                               "4463": [0.040, 78.176, 4.459]}.get(str(n_lines)),
            }
            artifacts.record("stage_parity", row)
            print(f"[opp] stage parity {n_lines} lines: {row}", file=sys.stderr)

    # Phase 3: engine end-to-end per sort mode at bench shapes.

    lines = bench.load_corpus(int(os.environ.get("LOCUST_OPP_AB_BYTES", 32 << 20)))
    corpus_bytes = sum(len(ln) + 1 for ln in lines)
    # One host-side conversion feeds every engine in phases 3 and 3.5
    # (identical line_width): rows_from_lines over a 32MB corpus costs
    # seconds of tunnel-window time per call.
    rows_ab = MapReduceEngine(EngineConfig(block_lines=32768)).rows_from_lines(lines)
    results = {}
    for mode in ("hash", "hash1", "radix"):
        eng = MapReduceEngine(EngineConfig(block_lines=32768, sort_mode=mode))
        blocks = eng.prepare_blocks(rows_ab)
        blocks.block_until_ready()
        t0 = time.perf_counter()
        eng.run_blocks(blocks)  # compile + warm
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(3):
            res = eng.run_blocks(blocks)
            best = min(best, res.times.total_ms / 1e3)
        results[mode] = {
            "mb_s": round(corpus_bytes / 1e6 / best, 2),
            "best_s": round(best, 4),
            "compile_s": round(compile_s, 1),
            "distinct": res.num_segments,
        }
        print(f"[opp] mode={mode}: {results[mode]}", file=sys.stderr)
    artifacts.record(
        "engine_sort_mode_ab",
        {"corpus_mb": round(corpus_bytes / 1e6, 1), "modes": results},
    )

    # Phase 3.5: block_lines tuning at the headline-bench shape — dispatch
    # granularity vs per-block sort size is the one free knob left.
    results = {}
    for bl in (16384, 32768, 65536):
        eng = MapReduceEngine(EngineConfig(block_lines=bl))
        blocks = eng.prepare_blocks(rows_ab)
        blocks.block_until_ready()
        eng.run_blocks(blocks)  # compile + warm
        best = float("inf")
        for _ in range(3):
            res = eng.run_blocks(blocks)
            best = min(best, res.times.total_ms / 1e3)
        results[str(bl)] = {
            "mb_s": round(corpus_bytes / 1e6 / best, 2),
            "best_s": round(best, 4),
        }
        print(f"[opp] block_lines={bl}: {results[str(bl)]}", file=sys.stderr)
    artifacts.record(
        "block_lines_ab",
        {"corpus_mb": round(corpus_bytes / 1e6, 1), "blocks": results},
    )

    # Phase 4 (optional): big streaming corpus in bounded RSS.
    stream_mb = int(os.environ.get("LOCUST_OPP_STREAM_MB", 0))
    if stream_mb:
        from locust_tpu.io.corpus import write_corpus
        from locust_tpu.io.loader import StreamingCorpus

        path = f"/tmp/opp_stream_{stream_mb}.txt"
        if not os.path.exists(path):
            write_corpus(path, stream_mb * 1_000_000, n_vocab=50_000)
        size = os.path.getsize(path)
        eng = MapReduceEngine(EngineConfig(block_lines=32768))
        t0 = time.perf_counter()
        res = eng.run_stream(StreamingCorpus(path, 128, 32768))
        wall = time.perf_counter() - t0
        rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
        row = {
            "corpus_mb": round(size / 1e6, 1),
            "wall_s": round(wall, 1),
            "mb_s": round(size / 1e6 / wall, 2),
            "distinct": res.num_segments,
            "truncated": res.truncated,
            "peak_rss_mb": round(rss_mb, 0),
        }
        artifacts.record("stream_scale", row)
        print(f"[opp] stream: {json.dumps(row)}", file=sys.stderr)

    print("[opp] sweep complete", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
