"""Opportunistic TPU evidence sweep: run whatever fits a tunnel-up window.

The remote-TPU tunnel flaps (VERDICT r2 missing #1); this script is the
one-shot "the tunnel is up, capture everything" bundle.  Each phase appends
rows to ``artifacts/tpu_runs.jsonl`` via locust_tpu.utils.artifacts, so a
partial window still leaves committed evidence.  Phase order is by
DECISION VALUE per compile-second (each already-session-answered phase is
skipped, see _session_row_ok):

  0. fused_ab: engine-level fused-megakernel vs hasht vs hasht-mxu rows
     (ordinary engine_sort_mode_ab rows, carried into phase 2's resume)
     — the first slot, before any compile-heavy phase can eat the window
  0.5. fused_stream_ab: the persistent STREAMING kernel vs hasht through
     run_stream (megakernel v2) — fused_stream/hasht_stream rows in the
     same engine_sort_mode_ab shape, right behind the batch verdict
  1. sort-variant bench at the engine's true Process-stage shape —
     only the PRODUCTIVE variants this session hasn't measured yet (the
     Pallas bitonic variant H is demoted to phase 3)
  2. the shared opp_resume phases: engine sort-mode A/B (hasht +
     hasht-mxu verdicts first, bitonic last — steers bench's evidence
     tuning) -> block/table/pallas A/Bs -> stage device-time
     decomposition -> profiler capture -> parity tables -> (optional,
     $LOCUST_OPP_STREAM_MB) bounded-RSS streaming
  3. the demoted bitonic phases: variant H (100.7 s compile for a
     measured 1.26x loser, VERDICT r5 item 4 — never before the
     productive rows), then the Pallas check battery
     (scripts/tpu_checks.py subprocess) — fused/tile ladders + tokenize
     checks, the window's long tail

Exit codes: 0 = all requested phases captured, 3 = tunnel down, 1 = error.
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from locust_tpu.config import machine_cache_dir  # noqa: E402 - jax-free

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", machine_cache_dir())

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Sibling module: ensure the scripts dir is importable even when THIS
# module is loaded by file path (tests) rather than executed as a script.
_SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))
if _SCRIPTS_DIR not in sys.path:
    sys.path.insert(0, _SCRIPTS_DIR)

import opp_resume  # noqa: E402


def _answered_variant_letters(n_rows: int) -> set:
    """Variant letters measured (a ``run_ms`` recorded) in a
    session-valid TPU sort_variants row AT THE SWEEP'S SHAPE — across
    rows, so a window that died mid-phase still retires the variants it
    DID measure and the next window re-pays only the remainder's tunnel
    compiles.  Session validity is ``opp_resume._session_row_ok`` (code
    fingerprint, legacy ts-floor fallback); the ``n_rows`` filter keeps
    a manual small-N spot-check (primitive timings are strongly
    shape-dependent; J measured 19x at 65k rows vs 2.2x at 720k) from
    standing in for the fold-true-shape verdict."""
    from locust_tpu.utils.artifacts import ledger_rows

    answered = set()
    for r in ledger_rows():
        if r.get("kind") != "sort_variants" or r.get("backend") != "tpu":
            continue
        if r.get("n_rows") != n_rows or not opp_resume._session_row_ok(r):
            continue
        for name, res in (r.get("variants") or {}).items():
            if isinstance(res, dict) and "run_ms" in res:
                answered.add(str(name).split("_")[0])
    return answered


def battery_answered() -> bool:
    """True iff the Pallas check battery needs no re-run this session.

    Requires BOTH a session-valid ``battery_complete`` marker AND usable
    rows for the battery's key checks (ADVICE r5): tpu_checks records
    battery_complete unconditionally at the end of main(), including
    when a transient Mosaic/tunnel failure left only error rows — the
    marker alone would mute the battery for 24h and _row_usable's
    re-attempt policy could never fire.  session_done_checks applies the
    same _session_row_ok + _row_usable rules as the battery's own
    per-check resume, so the two skip policies cannot diverge.
    """
    import tpu_checks

    from locust_tpu.utils.artifacts import latest_row_ts

    if latest_row_ts(
        "tpu_check",
        where=lambda r: (r.get("check") == "battery_complete"
                         and opp_resume._session_row_ok(r)),
    ) <= 0:
        return False
    key_checks = {"pallas_tokenizer_tpu", "map_ab"}
    return key_checks <= set(tpu_checks.session_done_checks())


def _run_phase(name: str, cmd: list, env: dict, timeout: float) -> None:
    """One subprocess phase; a timeout or crash here must not kill the
    phases behind it (a 560s variant overrun crashed the whole 07-31
    sweep before the engine A/Bs — the window's highest-value phases)."""
    try:
        r = subprocess.run(cmd, env=env, timeout=timeout,
                           capture_output=True, text=True)
        print(r.stdout, file=sys.stderr)
        if r.returncode != 0:
            print(f"[opp] {name} failed: {r.stderr[-500:]}", file=sys.stderr)
    except subprocess.TimeoutExpired as e:
        for stream in (e.stdout, e.stderr):
            s = stream or b""
            if isinstance(s, bytes):
                s = s.decode(errors="replace")
            if s.strip():
                # stderr carries the only clue WHICH step overran
                # (Mosaic error text, tracebacks) — keep its tail.
                print(s[-2000:], file=sys.stderr)
        print(f"[opp] {name} timed out after {timeout:.0f}s "
              f"(rows already appended stay; moving on)", file=sys.stderr)


def main() -> int:
    if not opp_resume.tunnel_gate():
        return 3

    # Phase 0: the fused megakernel's engine-level verdict — fused vs
    # hasht vs hasht-mxu rows in the FIRST window slot, before the
    # variant phase's 10-100s-per-letter tunnel compiles and before any
    # bitonic anything (ROADMAP item 5; ISSUE 13 arming requirement).
    # The rows are ordinary engine_sort_mode_ab rows, so the shared
    # phase 3 resumes past whatever landed here instead of re-measuring;
    # the staging is handed to run_phases below for the same reason.
    staged = opp_resume._guard("staging", opp_resume._staged_rows)
    if staged is not None:
        rows_ab, corpus_bytes, kw, epl = staged
        opp_resume._guard(
            "fused_ab",
            lambda: opp_resume.phase_fused_ab(
                rows_ab, corpus_bytes,
                caps={"key_width": kw, "emits_per_line": epl},
            ),
        )
        # Phase 0.5 (megakernel v2): the persistent STREAMING kernel's
        # verdict — fused_stream vs hasht_stream run_stream rows,
        # immediately after the batch fused verdict and still before
        # any compile-heavy phase.  Same engine_sort_mode_ab row shape,
        # so a window that dies after one side resumes past it.
        opp_resume._guard(
            "fused_stream_ab",
            lambda: opp_resume.phase_fused_stream_ab(
                rows_ab, corpus_bytes,
                caps={"key_width": kw, "emits_per_line": epl},
            ),
        )

    # Phase 1: sort variants at the engine shape (table + block emits).
    env = dict(os.environ)
    # Priority order (a short window should answer the open question
    # first): J = the hasht scatter primitive (VERDICT r4 next #2: is the
    # .at[].add serialized on TPU, the single biggest unknown on the
    # headline), K = the MXU-histogram primitive now productized as the
    # hasht-mxu engine mode, C = the payload-carry incumbent, then the
    # rest; radix (E/F) last — already measured losers (2.5-3x), only
    # re-timed if the window is generous.  H (the Pallas bitonic kernel)
    # is DEMOTED out of this phase entirely (VERDICT r5 item 4: 1.26x
    # loser, 100.7 s compile): it runs as its own phase AFTER the engine
    # A/Bs, so the hasht/hasht-mxu engine rows always land before any
    # bitonic compile can eat the window.  Once a window has answered
    # J/K (a TPU row covering them, < 24h old), later windows in the
    # same session skip straight to the engine phases — each variant
    # costs a fresh 10-100s tunnel compile, and re-answering a settled
    # primitive question starves the end-to-end A/Bs behind it.
    sweep_n = 65536 + 32768 * 20
    env["N"] = str(sweep_n)

    # "Answered" is SESSION-scoped: primarily by measurement-code
    # fingerprint (same compute path -> reusable row, regardless of farm
    # restarts), with a session-ts floor for legacy unstamped rows — the
    # ONE validity rule, opp_resume._session_row_ok, shared by both
    # sweep entry points.
    priority = ("J", "K", "I", "G", "C", "B", "D", "E", "F")
    answered = _answered_variant_letters(sweep_n)
    if not {"J", "K"} - answered:
        # The open questions are measured; the also-rans alone don't
        # justify re-paying a window's tunnel compiles.
        print("[opp] sort variants already answered this session "
              f"(answered: {sorted(answered)}); skipping", file=sys.stderr)
    else:
        # Only the UNANSWERED variants, priority order preserved: a
        # window that died after measuring J must spend its successor's
        # compiles on K, not on re-measuring J.
        env["LOCUST_SORT_VARIANTS"] = ",".join(
            v for v in priority if v not in answered
        )
        print(f"[opp] sort variants remaining: {env['LOCUST_SORT_VARIANTS']}",
              file=sys.stderr)
        _run_phase(
            "sort variants",
            [sys.executable,
             os.path.join(REPO, "scripts", "bench_sort_variants.py"),
             "--backend", "tpu"],
            env, 560,
        )

    # Phases 2.5 -> 4 are shared with the window-resume entry point
    # (scripts/opp_resume.py) so the two sweeps can never diverge.
    # They run BEFORE the Pallas check battery AND before the demoted
    # bitonic variant: the engine sort-mode A/B (fused + hasht +
    # hasht-mxu verdicts — the round's highest-expected-value unknowns,
    # and the input bench's evidence tuning adopts) must not starve
    # behind 560s of kernel-ladder compiles whose headline deliverable
    # (a Pallas hardware ms) is a measured loser (VERDICT r5 item 4).
    opp_resume.run_phases(staged=staged)

    # Demoted bitonic variant phase (H): only after the productive
    # engine-level A/Bs have had the window.  A 100.7 s compile for a
    # measured 1.26x loser is the LAST thing a scarce window should pay
    # for — but the ladder stays armed so a schedule fix can still be
    # vindicated on hardware.
    if "H" not in _answered_variant_letters(sweep_n):
        env_h = dict(os.environ)
        env_h["N"] = str(sweep_n)
        env_h["LOCUST_SORT_VARIANTS"] = "H"
        _run_phase(
            "sort variants (demoted bitonic)",
            [sys.executable,
             os.path.join(REPO, "scripts", "bench_sort_variants.py"),
             "--backend", "tpu"],
            env_h, 560,
        )

    # Drop the engine memo (compiled executables + any captured device
    # buffers) before spawning the battery: on the one-chip axon backend
    # the child's Pallas ladders allocate against whatever HBM this
    # parent still holds — the pre-reorder sweep spawned the battery
    # from an allocation-free parent, and that state must be restored.
    import gc

    opp_resume._ENGINES.clear()
    gc.collect()

    # Pallas check battery (separate process: own jit namespace) —
    # fused/tile ladders + tokenize checks, the window's long tail.
    # Retired by battery_answered(): the COMPLETE marker plus usable key
    # rows, so an error-only battery is re-attempted next window.
    if battery_answered():
        print("[opp] tpu_checks already answered this session; skipping",
              file=sys.stderr)
    else:
        _run_phase(
            "tpu_checks",
            [sys.executable, os.path.join(REPO, "scripts", "tpu_checks.py")],
            dict(os.environ), 560,
        )

    print("[opp] sweep complete", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
