"""Loopback data-plane microbench CLI (docs/DATAPLANE.md).

Runs the distributor fetch-path comparison (JSON/base64 vs binary
framing, raw vs zlib, window=1 vs window=K) against one in-process
worker on 127.0.0.1 and appends a ``dataplane_bench`` evidence row to
``artifacts/tpu_runs.jsonl`` via the shared ledger writer
(locust_tpu/utils/artifacts.py, ``force=True`` — this is host/socket
evidence, valid on any backend).

Usage:
    python scripts/bench_dataplane.py [--bytes N] [--chunk N] [--window K]
                                      [--repeats R] [--no-record]

Prints the result as one JSON document on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Pin CPU and drop the injected remote-TPU plugin BEFORE anything can
# touch a jax backend (the artifacts writer imports jax for row
# metadata; a wedged axon tunnel must not hang a pure-socket bench).
from locust_tpu.backend import force_cpu  # noqa: E402

force_cpu()

from locust_tpu.distributor.microbench import run_microbench  # noqa: E402
from locust_tpu.utils import artifacts  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bench_dataplane")
    p.add_argument("--bytes", type=int, default=4 << 20,
                   help="approx staged intermediate size (default 4MiB)")
    p.add_argument("--chunk", type=int, default=64 * 1024,
                   help="fetch chunk size (default 64KiB)")
    p.add_argument("--window", type=int, default=4,
                   help="pipelined chunks in flight (default 4)")
    p.add_argument("--repeats", type=int, default=3,
                   help="runs per variant; throughput is the best")
    p.add_argument("--no-record", action="store_true",
                   help="skip the artifacts ledger append")
    args = p.parse_args(argv)

    res = run_microbench(
        target_bytes=args.bytes,
        chunk_bytes=args.chunk,
        window=args.window,
        repeats=args.repeats,
    )
    if not args.no_record:
        # Kind imported from the two-sided registry, never re-spelled
        # (artifacts.BENCH_SUBDICT_KINDS — same discipline as
        # CONFIG_AB_KINDS).
        artifacts.record(
            artifacts.BENCH_SUBDICT_KINDS["dataplane"], res, force=True
        )
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
