"""North-star-scale streaming run with honest, fold-only RSS accounting.

VERDICT r3 next #4: the largest committed streaming artifact was 64MB and
its peak RSS was dominated by in-process corpus GENERATION.  This script
is the canonical ``stream_scale`` evidence producer:

  1. the Zipf corpus is pre-generated to disk by a SEPARATE process
     (bounded-memory chunked writer, io/corpus.write_corpus), so
     generation cost never pollutes the measurement;
  2. the measuring process then runs the bounded-memory streaming fold
     (auto-capped, prefetching StreamingCorpus -> engine.run_stream) and
     reports its OWN rss before the measure pass, before the fold, and
     the process peak — the fold's working-set delta is the bounded-RSS
     claim, on top of the jax runtime's fixed baseline;
  3. the output table is verified against a bounded-memory host oracle
     (streaming Counter over the same file: vocabulary-bounded, not
     corpus-bounded) -> ``token_oracle_match``.

Usage:
  python scripts/stream_scale.py --mb 512                  # CPU
  python scripts/stream_scale.py --mb 512 --backend tpu    # in a window

Appends a ``stream_scale`` row to artifacts/tpu_runs.jsonl (the artifact
hook records backend/device itself).  Match: reference loadFile slicing
(MapReduce/src/main.cu:40-64) at BASELINE.json north-star scale.
"""

import argparse
import collections
import json
import os
import re
import resource
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_VOCAB = 50_000


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


def ensure_corpus(path: str, mb: int) -> int:
    """Generate the corpus in a child process (its RSS is not ours)."""
    want = mb * 1_000_000
    if os.path.exists(path) and os.path.getsize(path) >= want:
        return os.path.getsize(path)
    code = (
        "import sys; sys.path.insert(0, %r); "
        "from locust_tpu.io.corpus import write_corpus; "
        "write_corpus(%r, %d, n_vocab=%d)" % (REPO, path, want, N_VOCAB)
    )
    t0 = time.perf_counter()
    subprocess.run(
        [sys.executable, "-c", code],
        check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
    )
    print(
        f"[stream] generated {os.path.getsize(path)/1e6:.0f} MB in child "
        f"process ({time.perf_counter()-t0:.0f}s)",
        file=sys.stderr,
    )
    return os.path.getsize(path)


def host_oracle(path: str, delimiters: bytes):
    """Bounded-memory oracle: total tokens + per-word counts, streamed.

    Memory is vocabulary-bounded (Counter over <= N_VOCAB + noise keys),
    never corpus-bounded.  Uses the device's FULL delimiter set so the
    comparison is exact, and the device's line_width truncation is NOT
    applied — the generator's 10 x 7B-token lines fit 128B rows, so
    truncation never fires on this corpus.
    """
    pat = re.compile(b"[" + re.escape(delimiters) + b"]+")
    counts: collections.Counter = collections.Counter()
    with open(path, "rb") as f:
        for ln in f:
            counts.update(t for t in pat.split(ln) if t)
    return counts


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=512)
    ap.add_argument("--path", default=None)
    ap.add_argument("--backend", choices=["auto", "cpu", "tpu"], default="cpu")
    ap.add_argument("--block-lines", type=int, default=32768)
    ap.add_argument("--skip-oracle", action="store_true",
                    help="skip the host verification pass (faster; the "
                         "row then reports token_oracle_match: null)")
    args = ap.parse_args()
    path = args.path or f"/tmp/stream_scale_{args.mb}mb.txt"

    size = ensure_corpus(path, args.mb)

    from locust_tpu.backend import select_backend

    backend = select_backend(args.backend, probe_timeout_s=90, retries=2)
    print(f"[stream] backend: {backend}", file=sys.stderr)

    import bench

    from locust_tpu.config import FULL_DELIMITERS, EngineConfig
    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.io.loader import (
        StreamingCorpus,
        measure_caps_stream,
        size_caps,
    )
    from locust_tpu.utils import artifacts

    rss_start = _rss_mb()
    d = EngineConfig()
    t0 = time.perf_counter()
    measure_stream = StreamingCorpus(path, d.line_width, args.block_lines)
    fp = measure_stream.fingerprint()
    max_tok, max_per_line = measure_caps_stream(measure_stream)
    kw, epl = size_caps(max_tok, max_per_line, d.key_width, d.emits_per_line)
    measure_s = time.perf_counter() - t0
    print(
        f"[stream] caps: key_width={kw} emits_per_line={epl} "
        f"({measure_s:.0f}s measure pass)",
        file=sys.stderr,
    )

    # table_size pinned to the default-caps resolution (bench_engine_config
    # policy) so the table is identical to a default-config run.
    eng = MapReduceEngine(
        bench.bench_engine_config(
            args.block_lines, key_width=kw, emits_per_line=epl
        )
    )
    run_src = StreamingCorpus(path, d.line_width, args.block_lines)
    if run_src.fingerprint() != fp:
        print("[stream] corpus changed between passes; abort", file=sys.stderr)
        return 1
    # Warm up compile + XLA runtime arenas BEFORE the RSS baseline: the
    # fold executable and its workspace are one-time allocations shared
    # with any corpus size; the bounded-RSS claim is about growth WITH
    # corpus size, so they belong to the baseline, not the fold delta.
    import numpy as np

    eng.run(np.zeros((1, d.line_width), np.uint8))
    rss_before_fold = _rss_mb()
    t0 = time.perf_counter()
    res = eng.run_stream(run_src)
    wall = time.perf_counter() - t0
    rss_peak = _rss_mb()

    # The fold's expected working set: the staging ring
    # (STREAM_DISPATCH_DEPTH + 1 reusable slots — the in-flight blocks
    # ARE ring slots now) + prefetch-held source blocks + the device
    # table mirrored at sync + host block assembly.
    block_mb = args.block_lines * d.line_width / 1e6
    expected_mb = (
        block_mb * (MapReduceEngine.STREAM_DISPATCH_DEPTH + 1 + 2)
        + eng.cfg.resolved_table_size * (kw + 8) / 1e6
    )

    match = None
    distinct_oracle = None
    if not args.skip_oracle:
        t0 = time.perf_counter()
        oracle = host_oracle(path, FULL_DELIMITERS)
        pairs = dict(res.to_host_pairs())
        match = pairs == dict(oracle)
        distinct_oracle = len(oracle)
        print(
            f"[stream] oracle: {len(oracle)} keys, match={match} "
            f"({time.perf_counter()-t0:.0f}s host pass)",
            file=sys.stderr,
        )

    row = {
        "corpus_mb": round(size / 1e6, 1),
        "wall_s": round(wall, 1),
        "mb_s": round(size / 1e6 / wall, 2),
        "caps": {"key_width": kw, "emits_per_line": epl},
        "block_lines": args.block_lines,
        "distinct": res.num_segments,
        "truncated": res.truncated,
        "rss_start_mb": round(rss_start, 0),
        "rss_before_fold_mb": round(rss_before_fold, 0),
        "peak_rss_mb": round(rss_peak, 0),
        "fold_delta_mb": round(rss_peak - rss_before_fold, 0),
        "expected_working_set_mb": round(expected_mb, 1),
        "stream": res.stream,  # zero-stall executor accounting
        "token_oracle_match": match,
        "note": "corpus pre-generated by a separate process; rss fields "
                "are the measuring process only",
    }
    # TPU rows ride the standard evidence hook; CPU rows persist to their
    # own committed ladder file (artifacts.record is TPU-gated by design).
    if not artifacts.record("stream_scale", row):
        os.makedirs(artifacts.artifacts_dir(), exist_ok=True)
        cpu_path = os.path.join(
            artifacts.artifacts_dir(), "stream_scale_cpu_r4.jsonl"
        )
        with open(cpu_path, "a") as f:
            f.write(json.dumps({"ts": round(time.time(), 1),
                                "kind": "stream_scale", "backend": backend,
                                **row}) + "\n")
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
