"""Measure the throughput cost of the accumulator table size.

The per-block merge sorts ``table_size + emits_per_block`` rows, so table
capacity is a throughput knob as well as a truncation knob
(VERDICT.md round-1 #9: pick the default from data, not vibes).

Usage: python scripts/bench_table_size.py [--backend auto|cpu|tpu]
Prints one JSON line per (table_size, vocab) cell.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from locust_tpu.config import machine_cache_dir  # noqa: E402 - jax-free

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", machine_cache_dir())


def corpus_lines(n_vocab: int, total_tokens: int, seed: int = 0) -> list[bytes]:
    """Zipf corpus: vocabulary of n_vocab words, ~total_tokens draws."""
    from locust_tpu.io.corpus import synthetic_corpus

    return synthetic_corpus(total_tokens * 8, n_vocab=n_vocab, seed=seed)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="auto", choices=["auto", "cpu", "tpu"])
    ap.add_argument("--block-lines", type=int, default=32768)
    ap.add_argument("--tokens", type=int, default=1_000_000)
    args = ap.parse_args()

    from locust_tpu.backend import select_backend

    select_backend(args.backend)
    import jax

    from locust_tpu.config import EngineConfig
    from locust_tpu.engine import MapReduceEngine

    for n_vocab in (5_000, 100_000):
        lines = corpus_lines(n_vocab, args.tokens)
        nbytes = sum(len(ln) + 1 for ln in lines)
        for tsize in (1 << 16, 1 << 17, 1 << 18):
            cfg = EngineConfig(block_lines=args.block_lines, table_size=tsize)
            eng = MapReduceEngine(cfg)
            blocks = eng.prepare_blocks(eng.rows_from_lines(lines))
            blocks.block_until_ready()
            eng.run_blocks(blocks)  # warmup/compile
            best_ms, res = float("inf"), None
            for _ in range(3):
                r = eng.run_blocks(blocks)
                if r.times.total_ms < best_ms:
                    best_ms, res = r.times.total_ms, r
            print(json.dumps({
                "backend": jax.default_backend(),
                "table_size": tsize,
                "vocab": n_vocab,
                "distinct": res.num_segments,
                "truncated": res.truncated,
                "ms": round(best_ms, 1),
                "mb_s": round(nbytes / 1e6 / (best_ms / 1e3), 2),
            }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
