"""Real-TPU validation battery (VERDICT.md round-1 #4/#10).

Run on hardware (the suite pins CPU):

    python scripts/tpu_checks.py

1. Compiles + executes the Pallas tokenizer kernel (interpret=False).
2. A/B times the Pallas vs jnp Map stage at bench shapes.
3. Prints one JSON line per check it RUNS (artifact-friendly); checks
   already answered this session (a usable row passing
   opp_resume._session_row_ok) are skipped with a stderr note and print
   nothing on stdout — the ledger row is the durable record, stdout is
   progress reporting.
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from locust_tpu.config import machine_cache_dir  # noqa: E402 - jax-free

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", machine_cache_dir())


def _row_usable(name: str, r: dict) -> bool:
    """Did this prior row actually ANSWER its check?  A row recording
    only failures must not retire the check — the re-attempt is the
    point (matches check 3's own errored-row re-attempt policy).  The
    ladders require every rung measured (one transiently-errored tile
    would otherwise be unmeasurable all session); the rescue counts as
    answered once ANY rung produced a hardware ms."""
    def rungs_ok(field, require_all):
        v = r.get(field)
        if not isinstance(v, dict) or not v:
            return False
        have = [isinstance(x, dict) and "ms" in x for x in v.values()]
        return all(have) if require_all else any(have)

    if name == "pallas_tokenizer_tpu":
        return "matches_jnp" in r
    if name == "map_ab":
        return "pallas_ms" in r
    if name == "bitonic_tile_ab":
        return rungs_ok("tiles", require_all=True)
    if name == "bitonic_fused_ab":
        return rungs_ok("fused", require_all=True)
    if name == "bitonic_rescue":
        return rungs_ok("rungs", require_all=False)
    return True


def session_done_checks() -> dict:
    """Session-valid USABLE battery rows by check name (newest wins) —
    the per-check resume input (same validity rule as the sweep's phase
    skips, opp_resume._session_row_ok, plus _row_usable): a battery
    killed mid-run re-pays only the unanswered checks' compiles next
    window; check 3's Mosaic compile alone is ~100s of a flapping
    window."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import opp_resume

    from locust_tpu.utils.artifacts import ledger_rows

    done: dict = {}
    for r in ledger_rows():
        if (r.get("kind") == "tpu_check" and r.get("backend") == "tpu"
                and r.get("check") and opp_resume._session_row_ok(r)
                and _row_usable(r["check"], r)):
            try:
                newer = float(r.get("ts") or 0) >= float(
                    done.get(r["check"], {}).get("ts") or 0
                )
            except (TypeError, ValueError):
                continue
            if newer:
                done[r["check"]] = r
    return done


def main() -> int:
    from locust_tpu.backend import select_backend

    select_backend("tpu", probe_timeout_s=240, retries=2)
    import jax
    import jax.numpy as jnp

    from locust_tpu.config import EngineConfig
    from locust_tpu.core import bytes_ops
    from locust_tpu.ops.map_stage import tokenize_block
    from locust_tpu.ops.pallas.tokenize import tokenize_block_pallas

    print(json.dumps({"check": "backend", "platform": jax.default_backend()}))

    cfg = EngineConfig(block_lines=4096, line_width=128)
    # Same corpus fallback chain as bench.py: hamlet -> shipped sample.
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    text = bench.load_corpus(256 * 1024)
    lines = (text * (cfg.block_lines // len(text) + 1))[: cfg.block_lines]
    rows = jnp.asarray(bytes_ops.strings_to_rows(lines, cfg.line_width))

    from locust_tpu.utils import artifacts

    done_rows = session_done_checks()

    def _skip(name: str, want_n: int | None = None) -> bool:
        row = done_rows.get(name)
        if row is None:
            return False
        if want_n is not None and row.get("n") != want_n:
            # Shape guard (ADVICE r5, matching check 3's reuse guard): a
            # session-valid row captured at a DIFFERENT n (e.g. a manual
            # small-N spot check) must not retire this run's ladder —
            # primitive timings are strongly shape-dependent, and its
            # tiles dict would seed check 5's baseline at the wrong shape.
            print(f"[tpu_checks] {name}: prior row is at n="
                  f"{row.get('n')} != {want_n}; re-running",
                  file=sys.stderr, flush=True)
            return False
        print(f"[tpu_checks] {name}: already answered this session; "
              f"skipping", file=sys.stderr, flush=True)
        return True

    # 1. Pallas kernel compiles + runs for real, and matches the jnp path.
    jit_tokenize = jax.jit(tokenize_block, static_argnames=("cfg",))
    if not _skip("pallas_tokenizer_tpu"):
        t0 = time.perf_counter()
        pk, pv, povf = tokenize_block_pallas(rows, cfg, interpret=False)
        jax.block_until_ready(pk)
        compile_s = time.perf_counter() - t0
        ref = jit_tokenize(rows, cfg=cfg)
        match = bool(
            jnp.array_equal(pk, ref.keys)
            and jnp.array_equal(pv, ref.valid)
            and int(povf) == int(ref.overflow)
        )
        row = {
            "check": "pallas_tokenizer_tpu",
            "compile_s": round(compile_s, 1),
            "matches_jnp": match,
        }
        print(json.dumps(row), flush=True)
        artifacts.record("tpu_check", row)

    # 2. A/B: pallas vs jnp map stage steady-state.
    def best_ms(fn, reps=5):
        fn()  # warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    if not _skip("map_ab"):
        # Both sides jitted: the engine runs the jnp tokenizer under jit,
        # so an eager jnp side would overstate the Pallas win.
        jnp_ms = best_ms(lambda: jit_tokenize(rows, cfg=cfg).keys)
        pal_ms = best_ms(
            lambda: tokenize_block_pallas(rows, cfg, interpret=False)[0]
        )
        row = {
            "check": "map_ab",
            "block_lines": cfg.block_lines,
            "line_width": cfg.line_width,
            "jnp_ms": round(jnp_ms, 3),
            "pallas_ms": round(pal_ms, 3),
            "pallas_speedup": round(jnp_ms / pal_ms, 2),
        }
        print(json.dumps(row), flush=True)
        artifacts.record("tpu_check", row)

    # 3. Pallas bitonic Process-stage sort: Mosaic compile + host-verified
    # correctness + A/B vs the best stock-sort mode at engine shape
    # (VERDICT r3 next #2).  Error-isolated: a Mosaic lowering failure
    # must leave checks 1-2's rows intact and still record the loss.
    import numpy as np

    n = 65536 + 32768 * 20  # table + emits: the fold's true sort shape
    rng = np.random.default_rng(3)
    # < 0xFFFFFFFF: the pad sentinel ties with real rows and may
    # displace their payloads (bitonic_sort docstring caveat).
    key = jnp.asarray(rng.integers(0, 2**32 - 1, n, dtype=np.uint32))
    pay = jnp.asarray(np.arange(n, dtype=np.int32))

    prior3 = done_rows.get("bitonic_sort_ab")
    if (prior3 and prior3.get("matches_oracle")
            and prior3.get("n") == n and "bitonic_ms" in prior3):
        # Reuse the session-valid VERIFIED measurement: the ladders below
        # only need its oracle verdict and ms seed, and skipping here
        # saves the kernel's ~100s Mosaic compile.  (An errored or
        # unverified prior row does NOT skip — the re-attempt IS the
        # point then.)
        row = {k: prior3[k] for k in ("check", "n", "compile_s",
                                      "matches_oracle", "bitonic_ms",
                                      "lax_sort_ms", "bitonic_speedup")
               if k in prior3}
        print("[tpu_checks] bitonic_sort_ab: reusing session-valid "
              "verified row; skipping compile", file=sys.stderr, flush=True)
    else:
        try:
            from locust_tpu.ops.pallas.sort import bitonic_sort

            sort_j = jax.jit(
                lambda k, p: bitonic_sort(k, (p,), interpret=False)
            )
            t0 = time.perf_counter()
            sk, (sp,) = sort_j(key, pay)
            jax.block_until_ready(sk)
            compile_s = time.perf_counter() - t0
            ok = bool(
                np.array_equal(np.asarray(sk), np.sort(np.asarray(key)))
                and np.array_equal(
                    np.asarray(key)[np.asarray(sp)], np.asarray(sk)
                )
            )

            lax_j = jax.jit(lambda k, p: jax.lax.sort((k, p), num_keys=1))
            bit_ms = best_ms(lambda: sort_j(key, pay)[0])
            lax_ms = best_ms(lambda: lax_j(key, pay)[0])
            row = {
                "check": "bitonic_sort_ab",
                "n": n,
                "compile_s": round(compile_s, 1),
                "matches_oracle": ok,
                "bitonic_ms": round(bit_ms, 3),
                "lax_sort_ms": round(lax_ms, 3),
                "bitonic_speedup": round(lax_ms / bit_ms, 2),
            }
        except Exception as e:  # noqa: BLE001 - record the loss
            row = {
                "check": "bitonic_sort_ab",
                "error": f"{type(e).__name__}: {e}"[:400],
            }
        print(json.dumps(row), flush=True)
        artifacts.record("tpu_check", row)

    def make_rung(key_arr, pay_arr):
        """Oracle-verified bitonic timing rung over the GIVEN arrays:
        compile, verify keys AND payload pairing, then time.  One body
        for the tile/fusion ladders and the rescue bisect so the
        oracle/timing protocol cannot drift between them; error-isolated
        per rung (a risky compile must not take down its ladder)."""
        from locust_tpu.ops.pallas.sort import bitonic_sort as _bs

        k_np = np.asarray(key_arr)
        k_sorted = np.sort(k_np)

        def bitonic_rung(label, **kw):
            try:
                f = jax.jit(functools.partial(_bs, interpret=False, **kw))
                t0 = time.perf_counter()
                sk, (sp,) = f(key_arr, (pay_arr,))
                jax.block_until_ready(sk)
                compile_s = time.perf_counter() - t0
                sk_np, sp_np = np.asarray(sk), np.asarray(sp)
                if not (
                    np.array_equal(sk_np, k_sorted)
                    and np.array_equal(k_np[sp_np], sk_np)
                ):
                    return {"error": "output failed oracle"}
                ms = best_ms(lambda f=f: f(key_arr, (pay_arr,))[0])
                print(f"[tpu_checks] bitonic {label}: {ms:.1f}ms",
                      file=sys.stderr, flush=True)
                return {"ms": round(ms, 3), "compile_s": round(compile_s, 1)}
            except Exception as e:  # noqa: BLE001 - record the rung's loss
                return {"error": f"{type(e).__name__}: {e}"[:300]}

        return bitonic_rung

    # 4/5. Bitonic tile + fusion-cap ladders: RETIRED from the
    # must-answer set (ISSUE 13 / docs/PERF.md "Bitonic settlement"):
    # the kernel's only hardware number is a 1.26-1.33x loss bought with
    # a 100.7 s compile, and the fused megakernel (engine mode "fused",
    # measured first by the sweep's fused_ab phase) carries the
    # hand-written-kernel thesis now — a window's ladder seconds belong
    # to it.  LOCUST_TPU_BITONIC_LADDERS=1 re-arms the ladders for a
    # deliberate schedule-fix vindication run; check 3's single verified
    # A/B point and the rescue bisect stay, so bitonic keeps exactly one
    # hardware anchor per session without eating the window.
    run_bitonic_ladders = (
        os.environ.get("LOCUST_TPU_BITONIC_LADDERS") == "1"
    )
    if "error" not in row and row.get("matches_oracle") and not run_bitonic_ladders:
        print("[tpu_checks] bitonic tile/fused ladders retired "
              "(docs/PERF.md; LOCUST_TPU_BITONIC_LADDERS=1 re-arms)",
              file=sys.stderr, flush=True)
    elif "error" not in row and row.get("matches_oracle"):
        from locust_tpu.ops.pallas.sort import TILE_ROWS

        bitonic_rung = make_rung(key, pay)

        # 4. Tile sweep: where is the VMEM-residency/round-trip knee?
        # The default tile reuses check 3's verified measurement — a
        # flapping window should spend its seconds on the NEW points.
        if not _skip("bitonic_tile_ab", want_n=n):
            tiles = {str(TILE_ROWS): {"ms": row["bitonic_ms"],
                                      "compile_s": 0.0,
                                      "note": "from bitonic_sort_ab"}}
            for tr in (128, 256, 512, 1024):
                if tr == TILE_ROWS:
                    continue  # already measured (and verified) by check 3
                tiles[str(tr)] = bitonic_rung(f"tile {tr}", tile_rows=tr)
            row4 = {"check": "bitonic_tile_ab", "n": n, "tiles": tiles}
            print(json.dumps(row4), flush=True)
            artifacts.record("tpu_check", row4)
        else:
            tiles = done_rows["bitonic_tile_ab"].get("tiles") or {}

        # 5. Fusion-cap ladder: the static default is capped at
        # config.BITONIC_MAX_FUSED because UNLIMITED fusion crashed
        # Mosaic on 2026-07-31 — but that crash predates the int32-mask
        # rewrite, so this ladder measures whether the cap is still
        # needed and what it costs.
        if not _skip("bitonic_fused_ab", want_n=n):
            from locust_tpu.config import BITONIC_MAX_FUSED

            fused = {str(BITONIC_MAX_FUSED): {
                "ms": (tiles.get(str(TILE_ROWS), {}).get("ms")
                       or row.get("bitonic_ms")),
                "note": "config default, from bitonic_tile_ab",
            }}
            for mf in (128, 0):
                if mf == BITONIC_MAX_FUSED:
                    continue
                fused[str(mf)] = bitonic_rung(f"max_fused={mf}",
                                              max_fused=mf)
            row5 = {"check": "bitonic_fused_ab", "n": n, "fused": fused}
            print(json.dumps(row5), flush=True)
            artifacts.record("tpu_check", row5)
    elif not _skip("bitonic_rescue"):
        # Rescue bisect (VERDICT r4 next #3: "bisect kernel size until
        # something compiles and commit whatever ms results"): the
        # default configuration failed, so walk simpler schedules —
        # tighter fusion caps first (fewer substages per Mosaic launch),
        # then a 64x smaller array — until ANY rung yields a hardware
        # millisecond.  Three rounds of zero kernel data is the failure
        # mode this ladder exists to end; each rung is oracle-verified
        # and error-isolated like the main ladders.
        rescue = {}
        rung_full = make_rung(key, pay)
        for mf in (8, 2, 1):
            rescue[f"n={n},max_fused={mf}"] = rung_full(
                f"rescue max_fused={mf}", max_fused=mf
            )
            if "ms" in rescue[f"n={n},max_fused={mf}"]:
                break
        if not any("ms" in v for v in rescue.values()):
            n_small = 1 << 16
            rung_small = make_rung(key[:n_small], pay[:n_small])
            for mf in (32, 1):
                rescue[f"n={n_small},max_fused={mf}"] = rung_small(
                    f"rescue n={n_small} max_fused={mf}", max_fused=mf
                )
                if "ms" in rescue[f"n={n_small},max_fused={mf}"]:
                    break
        row = {"check": "bitonic_rescue", "rungs": rescue}
        print(json.dumps(row), flush=True)
        artifacts.record("tpu_check", row)
    # Battery-complete marker: the sweep's session-skip keys on THIS row
    # (not the per-check crumbs above), so a battery killed mid-run is
    # re-attempted next window instead of counting as answered.
    row = {"check": "battery_complete"}
    print(json.dumps(row), flush=True)
    artifacts.record("tpu_check", row)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
