#!/usr/bin/env python
"""One-shot dev gate: static analysis + its test suite.

    env JAX_PLATFORMS=cpu python scripts/check.py [--fast]

Runs (1) the invariant checker over the configured paths (exit 1 on new
findings — docs/ANALYSIS.md) and (2) tests/test_analysis.py, which
includes the repo-wide gate test.  ``--fast`` skips the pytest half.
Exit code is non-zero if either half fails.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    fast = "--fast" in argv

    # In-process: the analyzer imports no checked code (and no jax).
    sys.path.insert(0, REPO)
    from locust_tpu.analysis import run_analysis

    result = run_analysis(root=REPO)
    for f in result.findings:
        print(f.format(), file=sys.stderr)
    print(
        f"[check] analysis: {len(result.new)} new finding(s) over "
        f"{result.n_files} file(s), {result.suppressed} suppressed",
        file=sys.stderr,
    )
    rc = 1 if result.new else 0
    if fast:
        return rc

    # Pinned env (R006 applies to this script too): the analyzer suite
    # runs pytest in a child python; the child must not be hangable by
    # the ambient axon sitecustomize.
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_analysis.py", "-q"],
        cwd=REPO, env=env, timeout=600,
    )
    print(
        f"[check] tests: rc={proc.returncode}; analysis rc={rc}",
        file=sys.stderr,
    )
    return rc or proc.returncode


if __name__ == "__main__":
    raise SystemExit(main())
