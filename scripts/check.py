#!/usr/bin/env python
"""One-shot dev gate: static analysis + its test suite + a traced run.

    env JAX_PLATFORMS=cpu python scripts/check.py [--fast]

Runs (1) the two-phase invariant checker (R001-R018) over the configured
paths (exit 1 on new findings — docs/ANALYSIS.md), with a --changed
pre-gate (findings on diff-touched lines reported first) and a SARIF
emission round-trip archived to the configured artifact path,
(2) tests/test_analysis.py, which includes the
repo-wide gate test, and (3) a small traced engine run whose exported
timeline is validated against locust_tpu/obs/trace.schema.json (the obs
contract, docs/OBSERVABILITY.md) — in a subprocess with a pinned env, so
this process stays jax-free.  ``--fast`` skips (2) and (3).
Exit code is non-zero if any part fails.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    fast = "--fast" in argv

    # In-process: the analyzer imports no checked code (and no jax).
    sys.path.insert(0, REPO)
    from locust_tpu.analysis import run_analysis
    from locust_tpu.analysis.core import changed_lines, scope_to_changed

    result = run_analysis(root=REPO)

    # --changed pre-gate: the findings on lines YOU touched, reported
    # FIRST — the thing a dev iterating on a diff actually wants to see
    # before the whole-tree report.  Same run (analysis is always
    # whole-program; the scope only narrows what is reported), so the
    # pre-gate costs nothing.  Skipped without complaint when git can't
    # diff (detached tmp checkouts).
    try:
        scoped = scope_to_changed(result, changed_lines(REPO, "HEAD"))
        if scoped.new:
            print("[check] pre-gate: new finding(s) on changed lines:",
                  file=sys.stderr)
            for f in scoped.new:
                print(f"  {f.format()}", file=sys.stderr)
        else:
            print("[check] pre-gate: changed lines clean", file=sys.stderr)
    except ValueError as e:
        print(f"[check] pre-gate skipped ({e})", file=sys.stderr)

    for f in result.findings:
        print(f.format(), file=sys.stderr)
    print(
        f"[check] analysis: {len(result.new)} new finding(s) over "
        f"{result.n_files} file(s), {result.suppressed} suppressed",
        file=sys.stderr,
    )
    rc = 1 if result.new else 0

    # SARIF emission round-trip + archive: the CI-annotation surface must
    # stay a loadable 2.1.0 log whatever the findings are, and the log is
    # ARCHIVED (config "sarif_artifact", gitignored) so the last gate
    # run's findings are inspectable after the fact (docs/ANALYSIS.md).
    import json

    from locust_tpu.analysis import config as _cfg
    from locust_tpu.analysis.registry import all_rules
    from locust_tpu.analysis.sarif import write_sarif

    sarif_path = os.path.join(
        REPO, _cfg.load_config(REPO)["sarif_artifact"]
    )
    os.makedirs(os.path.dirname(sarif_path), exist_ok=True)
    write_sarif(sarif_path, result, dict(all_rules()))
    with open(sarif_path, encoding="utf-8") as fh:
        doc = json.load(fh)
    drv = doc["runs"][0]["tool"]["driver"]
    if (
        doc.get("version") != "2.1.0"
        or not all("helpUri" in r for r in drv["rules"])
    ):
        print("[check] sarif round-trip: bad version or rule metadata",
              file=sys.stderr)
        rc = rc or 1
    else:
        print(f"[check] sarif archived to {sarif_path}", file=sys.stderr)
    if fast:
        return rc

    # Pinned env (R006 applies to this script too): the analyzer suite
    # runs pytest in a child python; the child must not be hangable by
    # the ambient axon sitecustomize.
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_analysis.py", "-q"],
        cwd=REPO, env=env, timeout=600,
    )

    # Traced round-trip: a tiny engine run under the obs tracer, exported
    # and schema-validated — the telemetry contract every --trace-out run
    # rides.  Subprocess (same pinned env) keeps THIS process jax-free.
    trace_rc = subprocess.run(
        [sys.executable, "-c", _TRACE_ROUNDTRIP], cwd=REPO, env=env,
        timeout=300,
    ).returncode

    # Serve-tier smoke (docs/SERVING.md): a loopback daemon serves a
    # submit, a result-cache repeat, and a same-bucket warm dispatch,
    # then shuts down cleanly — the zero-to-serving contract the CLI
    # (`python -m locust_tpu.serve`) rides.  Same pinned env.
    serve_rc = subprocess.run(
        [sys.executable, "-c", _SERVE_SMOKE], cwd=REPO, env=env,
        timeout=300,
    ).returncode

    # Crash-recovery smoke (docs/SERVING.md "Durability guarantee"): a
    # REAL daemon process is SIGKILL'd mid-job, restarted on the same
    # write-ahead journal, and the replayed result must be byte-identical
    # to the one-shot CLI over the same corpus/config.  Same pinned env.
    recovery_rc = subprocess.run(
        [sys.executable, "-c", _RECOVERY_SMOKE], cwd=REPO, env=env,
        timeout=300,
    ).returncode

    # Scale-out pool smoke (docs/SERVING.md "Scale-out dispatch"): a
    # daemon over TWO real worker processes serves a submit exactly,
    # then one worker is SIGKILL'd mid-serve-batch and the retried
    # result must STILL be byte-identical to the one-shot CLI — worker
    # death costs latency, never an answer.  Same pinned env.
    pool_rc = subprocess.run(
        [sys.executable, "-c", _POOL_SMOKE], cwd=REPO, env=env,
        timeout=420,
    ).returncode

    # Plan smoke (docs/PLAN.md): a two-stage tf-idf PLAN submitted to a
    # real daemon must answer byte-identically to the one-shot
    # `python -m locust_tpu tfidf` CLI over the same corpus, and a
    # repeat must be a result-cache hit keyed by the plan fingerprint.
    # The recovery smoke above additionally SIGKILLs a daemon holding a
    # journaled plan job and diffs its replay the same way.
    plan_rc = subprocess.run(
        [sys.executable, "-c", _PLAN_SMOKE], cwd=REPO, env=env,
        timeout=300,
    ).returncode

    # Distributed-plan smoke (docs/PLAN.md "Distributed execution"): the
    # same two-stage tfidf plan across TWO real --serve workers, one
    # SIGKILL'd mid-map-stage (held open by an injected delay), and the
    # answer must STILL be byte-identical to the one-shot tfidf CLI —
    # stage-granular recompute on the survivor, never a wrong answer.
    dplan_rc = subprocess.run(
        [sys.executable, "-c", _DPLAN_SMOKE], cwd=REPO, env=env,
        timeout=420,
    ).returncode

    # Fused-stream smoke (docs/PERF.md "Megakernel v2"): the persistent
    # STREAMING formulation of the map->aggregate megakernel — a
    # `--stream --sort-mode fused` CLI run over 20 blocks (3 segments,
    # the last partial) must be byte-identical to the one-shot hasht
    # CLI, and the stream stats must show the streaming formulation
    # actually engaged (not a demotion).  Same pinned env.
    fused_stream_rc = subprocess.run(
        [sys.executable, "-c", _FUSED_STREAM_SMOKE], cwd=REPO, env=env,
        timeout=300,
    ).returncode

    # Machine-death failover smoke (docs/SERVING.md "High
    # availability"): a REAL primary+standby pair, the primary
    # SIGKILL'd holding a wordcount AND a journaled plan job, the
    # standby promoted via the CLI — both replays byte-identical to
    # the one-shot CLIs — and the zombie primary's restart fenced with
    # stale_epoch down to a not_primary-answering standby.
    failover_rc = subprocess.run(
        [sys.executable, "-c", _FAILOVER_SMOKE], cwd=REPO, env=env,
        timeout=420,
    ).returncode
    print(
        f"[check] tests: rc={proc.returncode}; analysis rc={rc}; "
        f"trace round-trip rc={trace_rc}; serve smoke rc={serve_rc}; "
        f"recovery smoke rc={recovery_rc}; pool smoke rc={pool_rc}; "
        f"plan smoke rc={plan_rc}; dplan smoke rc={dplan_rc}; "
        f"fused-stream smoke rc={fused_stream_rc}; "
        f"failover smoke rc={failover_rc}",
        file=sys.stderr,
    )
    return (rc or proc.returncode or trace_rc or serve_rc
            or recovery_rc or pool_rc or plan_rc or dplan_rc
            or fused_stream_rc or failover_rc)


_TRACE_ROUNDTRIP = """
import sys, tempfile, os
from locust_tpu.backend import force_cpu
force_cpu()
from locust_tpu import obs
from locust_tpu.config import EngineConfig
from locust_tpu.engine import MapReduceEngine
from locust_tpu.obs.schema import validate_trace
obs.enable(process="check")
eng = MapReduceEngine(
    EngineConfig(block_lines=8, line_width=32, key_width=8, emits_per_line=4)
)
eng.timed_run(eng.rows_from_lines([b"a b a", b"b c", b"c a b"]))
with tempfile.TemporaryDirectory() as td:
    path = os.path.join(td, "check.trace.json")
    doc = obs.export(path)
    validate_trace(doc)
    names = {e["name"] for e in doc["traceEvents"]}
need = {"engine.stage.map", "engine.stage.process", "engine.stage.reduce"}
missing = need - names
if missing:
    print(f"[check] trace round-trip missing spans: {missing}",
          file=sys.stderr)
    sys.exit(1)
print(f"[check] trace round-trip ok ({len(names)} span/event names)",
      file=sys.stderr)
"""


_SERVE_SMOKE = """
import sys
from locust_tpu.backend import force_cpu
force_cpu()
from locust_tpu.serve import ServeClient, ServeConfig, ServeDaemon
cfgov = {"block_lines": 8, "line_width": 64, "key_width": 16,
         "emits_per_line": 8}
daemon = ServeDaemon(secret=b"check-smoke", cfg=ServeConfig(max_batch=2))
daemon.serve_in_thread()
client = ServeClient(daemon.addr, b"check-smoke", timeout=60.0)
corpus = b"alpha beta gamma\\nbeta gamma delta\\n" * 6
ack = client.submit(corpus=corpus, config=cfgov)
res = client.wait(ack["job_id"], timeout=120.0)
assert dict(res["pairs"]) == {b"alpha": 6, b"beta": 12, b"gamma": 12,
                              b"delta": 6}, res["pairs"]
ack2 = client.submit(corpus=corpus, config=cfgov)
assert ack2["cached"] is True, ack2
ack3 = client.submit(corpus=corpus, config=cfgov, invalidate=True)
res3 = client.wait(ack3["job_id"], timeout=120.0)
assert res3["cache"] == "warm", res3  # same bucket: skipped compilation
assert dict(res3["pairs"]) == dict(res["pairs"])
client.shutdown()
daemon.close()
print("[check] serve smoke ok (result-cache + warm-executable hits)",
      file=sys.stderr)
"""


_RECOVERY_SMOKE = """
import os, signal, subprocess, sys, tempfile

td = tempfile.mkdtemp(prefix="locust_recovery_smoke_")
corpus_path = os.path.join(td, "corpus.txt")
with open(corpus_path, "wb") as f:
    f.write(b"alpha beta gamma\\nbeta gamma delta\\n" * 8)
cfg_flags = ["--block-lines", "8", "--line-width", "64",
             "--key-width", "16", "--emits-per-line", "8"]
env = {**os.environ, "JAX_PLATFORMS": "cpu",
       "PYTHONPATH": os.getcwd(), "LOCUST_SECRET": "recovery-smoke"}

# The oracles: the one-shot CLI over the same corpus + caps, for the
# WordCount job AND the two-stage tf-idf PLAN job (docs/PLAN.md).
one_shot = subprocess.run(
    [sys.executable, "-m", "locust_tpu", corpus_path,
     "--backend", "cpu", "--no-timing"] + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert one_shot.returncode == 0, one_shot.stderr[-800:]
tfidf_shot = subprocess.run(
    [sys.executable, "-m", "locust_tpu", "tfidf", corpus_path,
     "--backend", "cpu", "--lines-per-doc", "2"] + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert tfidf_shot.returncode == 0, tfidf_shot.stderr[-800:]

def spawn(env=env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "locust_tpu.serve", "--port", "0",
         "--journal-dir", os.path.join(td, "journal")],
        env=env, stderr=subprocess.PIPE, text=True,
    )
    line = proc.stderr.readline()
    assert "listening on" in line, line
    host, _, port = line.rsplit(" ", 1)[1].strip().partition(":")
    return proc, (host, int(port))

from locust_tpu.plan import tfidf_plan
from locust_tpu.serve.client import ServeClient

proc, addr = spawn()
try:
    client = ServeClient(addr, b"recovery-smoke", timeout=30.0)
    cfgov = {"block_lines": 8, "line_width": 64, "key_width": 16,
             "emits_per_line": 8}
    corpus = open(corpus_path, "rb").read()
    job_id = client.submit(corpus=corpus, config=cfgov,
                           no_cache=True)["job_id"]
    # A journaled PLAN job rides the same crash: the WAL admit record
    # carries the whole plan document, so the restart must re-execute
    # the arbitrary pipeline under its original id (docs/PLAN.md).
    plan_id = client.submit(corpus=corpus, config=cfgov,
                            plan=tfidf_plan(2).to_doc(),
                            no_cache=True)["job_id"]
    # SIGKILL right behind the acks: the jobs are queued-or-mid-
    # dispatch, exactly the lost-work window the journal closes.
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)
finally:
    if proc.poll() is None:
        proc.kill()
proc2, addr2 = spawn()
try:
    c2 = ServeClient(addr2, b"recovery-smoke", timeout=30.0)
    res = c2.wait(job_id, timeout=240.0)
    got = b"".join(
        k + b"\\t" + str(v).encode() + b"\\n"
        for k, v in sorted(res["pairs"])
    )
    assert got == one_shot.stdout, (
        "replayed result != one-shot CLI\\n%r\\n%r"
        % (got[:200], one_shot.stdout[:200])
    )
    pres = c2.wait(plan_id, timeout=240.0)
    assert pres.get("plan") is True, pres.get("plan")
    assert pres["pairs"][0][0] == tfidf_shot.stdout, (
        "replayed plan result != one-shot tfidf CLI\\n%r\\n%r"
        % (pres["pairs"][0][0][:200], tfidf_shot.stdout[:200])
    )
    c2.shutdown()
    proc2.wait(timeout=30)
finally:
    if proc2.poll() is None:
        proc2.kill()
print("[check] recovery smoke ok (SIGKILL mid-job -> wordcount AND "
      "plan replays byte-identical to the one-shot CLI)",
      file=sys.stderr)
"""


_POOL_SMOKE = """
import json, os, signal, subprocess, sys, tempfile, time

td = tempfile.mkdtemp(prefix="locust_pool_smoke_")
corpus_path = os.path.join(td, "corpus.txt")
with open(corpus_path, "wb") as f:
    f.write(b"alpha beta gamma\\nbeta gamma delta\\n" * 8)
cfg_flags = ["--block-lines", "8", "--line-width", "64",
             "--key-width", "16", "--emits-per-line", "8"]
env = {**os.environ, "JAX_PLATFORMS": "cpu",
       "PYTHONPATH": os.getcwd(), "LOCUST_SECRET": "pool-smoke"}

one_shot = subprocess.run(
    [sys.executable, "-m", "locust_tpu", corpus_path,
     "--backend", "cpu", "--no-timing"] + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert one_shot.returncode == 0, one_shot.stderr[-800:]

def spawn_worker():
    # Workers hold their SECOND serve_batch 3s (rpc.delay, after: 1) so
    # the SIGKILL below provably lands MID-serve-batch: the first job
    # dispatches clean and warms the worker, the same-bucket repeat is
    # routed back to it by affinity and held inside the dispatch.
    wenv = dict(env, LOCUST_FAULT_PLAN=json.dumps({"seed": 7, "rules": [
        {"site": "rpc.delay", "action": "delay", "delay_s": 3.0,
         "match": {"cmd": "serve_batch"}, "after": 1, "times": 1}]}))
    proc = subprocess.Popen(
        [sys.executable, "-m", "locust_tpu.distributor.worker",
         "--serve", "--port", "0"],
        env=wenv, stderr=subprocess.PIPE, text=True,
    )
    line = proc.stderr.readline()
    assert "listening on" in line, line
    host, _, port = line.rsplit(" ", 1)[1].strip().partition(":")
    return proc, f"{host}:{port}"

w1, a1 = spawn_worker()
w2, a2 = spawn_worker()
daemon = subprocess.Popen(
    [sys.executable, "-m", "locust_tpu.serve", "--port", "0",
     "--workers", f"{a1},{a2}"],
    env=env, stderr=subprocess.PIPE, text=True,
)
try:
    line = daemon.stderr.readline()
    assert "listening on" in line, line
    host, _, port = line.rsplit(" ", 1)[1].strip().partition(":")
    from locust_tpu.serve.client import ServeClient
    client = ServeClient((host, int(port)), b"pool-smoke", timeout=60.0)
    cfgov = {"block_lines": 8, "line_width": 64, "key_width": 16,
             "emits_per_line": 8}
    corpus = open(corpus_path, "rb").read()

    def as_cli(pairs):
        return b"".join(
            k + b"\\t" + str(v).encode() + b"\\n" for k, v in sorted(pairs)
        )

    jid = client.submit(corpus=corpus, config=cfgov,
                        no_cache=True)["job_id"]
    res = client.wait(jid, timeout=240.0)
    assert as_cli(res["pairs"]) == one_shot.stdout, "pool != one-shot CLI"
    placed = client.status(jid)["placed_on"]
    victim = w1 if placed == a1 else w2
    survivor_addr = a2 if placed == a1 else a1

    # Same-SHAPE repeat (same line count -> same bucket): affinity sends
    # it to the warm worker, whose serve_batch is held 3s by the fault
    # rule — SIGKILL it mid-batch.
    corpus2 = corpus.replace(b"alpha", b"omega")
    j2 = client.submit(corpus=corpus2, config=cfgov,
                       no_cache=True)["job_id"]
    time.sleep(0.8)
    victim.send_signal(signal.SIGKILL)
    victim.wait(timeout=10)
    res2 = client.wait(j2, timeout=240.0)
    p2 = os.path.join(td, "corpus2.txt")
    with open(p2, "wb") as f:
        f.write(corpus2)
    oracle2 = subprocess.run(
        [sys.executable, "-m", "locust_tpu", p2,
         "--backend", "cpu", "--no-timing"] + cfg_flags,
        env=env, capture_output=True, timeout=240,
    )
    assert oracle2.returncode == 0, oracle2.stderr[-800:]
    assert as_cli(res2["pairs"]) == oracle2.stdout, (
        "post-worker-death result != one-shot CLI"
    )
    st2 = client.status(j2)
    assert st2["placed_on"] != placed, st2
    client.shutdown()
    daemon.wait(timeout=60)
finally:
    for p in (w1, w2, daemon):
        if p.poll() is None:
            p.kill()
print("[check] pool smoke ok (2 real workers; SIGKILL mid-serve-batch "
      "-> retried result byte-identical to the one-shot CLI)",
      file=sys.stderr)
"""


_PLAN_SMOKE = """
import json, os, subprocess, sys, tempfile

td = tempfile.mkdtemp(prefix="locust_plan_smoke_")
corpus_path = os.path.join(td, "corpus.txt")
with open(corpus_path, "wb") as f:
    f.write(b"alpha beta gamma\\nbeta gamma delta\\nalpha alpha\\n"
            b"epsilon zeta\\n" * 4)
cfg_flags = ["--block-lines", "8", "--line-width", "64",
             "--key-width", "16", "--emits-per-line", "8"]
env = {**os.environ, "JAX_PLATFORMS": "cpu",
       "PYTHONPATH": os.getcwd(), "LOCUST_SECRET": "plan-smoke"}

# The oracle: the one-shot hand-wired tfidf CLI over the same corpus.
one_shot = subprocess.run(
    [sys.executable, "-m", "locust_tpu", "tfidf", corpus_path,
     "--backend", "cpu", "--lines-per-doc", "2"] + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert one_shot.returncode == 0, one_shot.stderr[-800:]

# The same pipeline as a PLAN document, submitted through the serve CLI
# (`submit FILE --plan PLAN.json`) against a real daemon.
from locust_tpu.plan import tfidf_plan

plan_path = os.path.join(td, "tfidf_plan.json")
with open(plan_path, "w") as f:
    json.dump(tfidf_plan(2).to_doc(), f)

daemon = subprocess.Popen(
    [sys.executable, "-m", "locust_tpu.serve", "--port", "0"],
    env=env, stderr=subprocess.PIPE, text=True,
)
try:
    line = daemon.stderr.readline()
    assert "listening on" in line, line
    host, _, port = line.rsplit(" ", 1)[1].strip().partition(":")
    submit = [sys.executable, "-m", "locust_tpu.serve", "submit",
              corpus_path, "--plan", plan_path, "--port", port] + cfg_flags
    cold = subprocess.run(submit, env=env, capture_output=True,
                          timeout=240)
    assert cold.returncode == 0, cold.stderr[-800:]
    assert cold.stdout == one_shot.stdout, (
        "plan submit != one-shot tfidf CLI\\n%r\\n%r"
        % (cold.stdout[:200], one_shot.stdout[:200])
    )
    # Repeat: a result-cache hit keyed by the plan fingerprint, still
    # byte-identical.
    warm = subprocess.run(submit, env=env, capture_output=True,
                          timeout=240)
    assert warm.returncode == 0, warm.stderr[-800:]
    assert warm.stdout == one_shot.stdout
    assert b"(cached)" in warm.stderr, warm.stderr[-400:]

    # Cross-tenant sub-plan sharing (docs/PLAN.md "Optimizer"): an
    # alpha-RENAMED tfidf plan — different plan fingerprint, so the
    # whole-job result cache MISSES — over the same corpus lands on the
    # per-edge entry the first tenant populated.
    doc = tfidf_plan(2).to_doc()
    for n in doc["nodes"]:
        n["id"] = "x_" + n["id"]
        n["inputs"] = ["x_" + r for r in n["inputs"]]
    plan2_path = os.path.join(td, "tfidf_plan_renamed.json")
    with open(plan2_path, "w") as f:
        json.dump(doc, f)
    ten2 = subprocess.run(
        [sys.executable, "-m", "locust_tpu.serve", "submit", corpus_path,
         "--plan", plan2_path, "--tenant", "t2", "--port", port]
        + cfg_flags,
        env=env, capture_output=True, timeout=240,
    )
    assert ten2.returncode == 0, ten2.stderr[-800:]
    assert ten2.stdout == one_shot.stdout, (
        "alpha-renamed plan != one-shot tfidf CLI"
    )
    assert b"(cached)" not in ten2.stderr  # not a whole-job cache hit

    # Incremental resubmit: the corpus grows APPEND-ONLY; the daemon
    # verifies the prefix sha server-side, re-folds only the delta
    # blocks, and the result must still be byte-identical to a cold
    # one-shot CLI over the grown corpus.
    with open(corpus_path, "rb") as f:
        base = f.read()
    grown_path = os.path.join(td, "corpus_grown.txt")
    with open(grown_path, "wb") as f:
        f.write(base + b"eta theta\\nalpha eta\\n" * 8)
    cold_grown = subprocess.run(
        [sys.executable, "-m", "locust_tpu", "tfidf", grown_path,
         "--backend", "cpu", "--lines-per-doc", "2"] + cfg_flags,
        env=env, capture_output=True, timeout=240,
    )
    assert cold_grown.returncode == 0, cold_grown.stderr[-800:]
    inc = subprocess.run(
        [sys.executable, "-m", "locust_tpu.serve", "submit", grown_path,
         "--plan", plan_path, "--port", port] + cfg_flags,
        env=env, capture_output=True, timeout=240,
    )
    assert inc.returncode == 0, inc.stderr[-800:]
    assert inc.stdout == cold_grown.stdout, (
        "incremental resubmit != cold one-shot CLI over the grown corpus"
    )
    stats = subprocess.run(
        [sys.executable, "-m", "locust_tpu.serve", "stats",
         "--port", port],
        env=env, capture_output=True, timeout=60,
    )
    assert stats.returncode == 0, stats.stderr[-800:]
    sub = json.loads(stats.stdout)["subplan_cache"]
    assert sub["hits"] >= 1, sub              # renamed tenant hit the edge
    assert sub["incremental_hits"] >= 1, sub  # the delta refold engaged
    assert 0 < sub["last_delta_blocks"] < sub["last_total_blocks"], sub

    subprocess.run(
        [sys.executable, "-m", "locust_tpu.serve", "shutdown",
         "--port", port],
        env=env, capture_output=True, timeout=60,
    )
    daemon.wait(timeout=30)
finally:
    if daemon.poll() is None:
        daemon.kill()
print("[check] plan smoke ok (two-stage tfidf plan byte-identical to "
      "the one-shot CLI, repeat = plan-keyed result-cache hit; "
      "alpha-renamed second tenant = sub-plan edge hit; append-only "
      "regrowth = incremental delta refold, still byte-identical)",
      file=sys.stderr)
"""


_DPLAN_SMOKE = """
import json, os, signal, subprocess, sys, tempfile, time

td = tempfile.mkdtemp(prefix="locust_dplan_smoke_")
corpus_path = os.path.join(td, "corpus.txt")
with open(corpus_path, "wb") as f:
    f.write(b"alpha beta gamma\\nbeta gamma delta\\nalpha alpha\\n"
            b"epsilon zeta\\n" * 8)
cfg_flags = ["--block-lines", "8", "--line-width", "64",
             "--key-width", "16", "--emits-per-line", "8"]
env = {**os.environ, "JAX_PLATFORMS": "cpu",
       "PYTHONPATH": os.getcwd(), "LOCUST_SECRET": "dplan-smoke"}

# The oracle: the one-shot hand-wired tfidf CLI over the same corpus.
one_shot = subprocess.run(
    [sys.executable, "-m", "locust_tpu", "tfidf", corpus_path,
     "--backend", "cpu", "--lines-per-doc", "2"] + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert one_shot.returncode == 0, one_shot.stderr[-800:]

from locust_tpu.plan import tfidf_plan

plan_path = os.path.join(td, "tfidf_plan.json")
with open(plan_path, "w") as f:
    json.dump(tfidf_plan(2).to_doc(), f)

def spawn_worker(fault=None):
    wenv = dict(env)
    if fault is not None:
        wenv["LOCUST_FAULT_PLAN"] = json.dumps(fault)
    proc = subprocess.Popen(
        [sys.executable, "-m", "locust_tpu.distributor.worker",
         "--serve", "--port", "0"],
        env=wenv, stderr=subprocess.PIPE, text=True,
    )
    line = proc.stderr.readline()
    assert "listening on" in line, line
    host, _, port = line.rsplit(" ", 1)[1].strip().partition(":")
    return proc, f"{host}:{port}"

# w2 holds its first map stage open 6s: the SIGKILL below provably
# lands MID-stage, and the coordinator must recompute that split on
# the survivor from the durable corpus spill.
w1, a1 = spawn_worker()
w2, a2 = spawn_worker(fault={"seed": 7, "rules": [
    {"site": "plan.stage", "action": "delay", "delay_s": 6.0,
     "match": {"phase": "map"}, "times": 1}]})
daemon = subprocess.Popen(
    [sys.executable, "-m", "locust_tpu.serve", "--port", "0",
     "--workers", f"{a1},{a2}", "--shard-min-blocks", "1"],
    env=env, stderr=subprocess.PIPE, text=True,
)
try:
    line = daemon.stderr.readline()
    assert "listening on" in line, line
    host, _, port = line.rsplit(" ", 1)[1].strip().partition(":")
    submit = subprocess.Popen(
        [sys.executable, "-m", "locust_tpu.serve", "submit",
         corpus_path, "--plan", plan_path, "--port", port] + cfg_flags,
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    # Kill w2 only once the map wave is provably in flight (w2's split
    # is held open by its fault plan while w1's lands) — a blind sleep
    # races a slow admit and can kill w2 BEFORE placement, demoting the
    # job to solo instead of exercising the mid-stage recompute.
    from locust_tpu.serve.client import ServeClient
    client = ServeClient((host, int(port)), b"dplan-smoke", timeout=60.0)
    deadline = time.time() + 120.0
    while time.time() < deadline:
        try:
            pl = client.stats()["pool"]["plan"]
        except Exception:
            pl = {}
        if pl.get("stages", 0) >= 1:
            break
        time.sleep(0.2)
    else:
        raise AssertionError("map wave never started: %r" % (pl,))
    time.sleep(0.5)
    w2.send_signal(signal.SIGKILL)
    w2.wait(timeout=10)
    out, err = submit.communicate(timeout=240)
    assert submit.returncode == 0, err[-800:]
    assert out == one_shot.stdout, (
        "distributed plan != one-shot tfidf CLI\\n%r\\n%r"
        % (out[:200], one_shot.stdout[:200])
    )
    pl = client.stats()["pool"]["plan"]
    assert pl["stages"] >= 4, pl      # it really ran distributed
    assert pl["recomputes"] >= 1, pl  # and really lost a stage
    client.shutdown()
    daemon.wait(timeout=60)
finally:
    for p in (w1, w2, daemon):
        if p.poll() is None:
            p.kill()
print("[check] dplan smoke ok (tfidf plan across 2 real workers; "
      "SIGKILL mid-map-stage -> survivor recompute, byte-identical "
      "to the one-shot CLI)", file=sys.stderr)

# ---- Plan surface v2 drills: SIGKILL mid-JOIN-stage and mid-pagerank-
# EPOCH.  Oracle = the same plan submitted to a solo (poolless) daemon;
# the distributed answer must be byte-identical even with a worker
# killed while its stage is provably in flight (the fault plan holds
# that stage open, and the kill lands inside the hold).
from locust_tpu.plan import pagerank_plan
from locust_tpu.plan.nodes import Plan, node
from locust_tpu.serve.client import ServeClient

join_doc = Plan((
    node("c1", "source", "text"),
    node("m1", "map", "tokenize_count", ("c1",)),
    node("s1", "shuffle", "by_key", ("m1",)),
    node("r1", "reduce", "sum", ("s1",)),
    node("c2", "source", "text"),
    node("m2", "map", "tokenize_count", ("c2",)),
    node("s2", "shuffle", "by_key", ("m2",)),
    node("r2", "reduce", "sum", ("s2",)),
    node("j1", "join", "inner", ("r1", "r2"), combine="mul"),
    node("out", "sink", "table", ("j1",)),
)).to_doc()
join_path = os.path.join(td, "join_plan.json")
pr_path = os.path.join(td, "pr_plan.json")
edges_path = os.path.join(td, "edges.txt")
with open(join_path, "w") as f:
    json.dump(join_doc, f)
with open(pr_path, "w") as f:
    json.dump(pagerank_plan(4).to_doc(), f)
with open(edges_path, "wb") as f:
    f.write(b"0 1\\n1 2\\n2 0\\n0 2\\n3 1\\n2 3\\n" * 3)

def spawn_daemon(workers=None):
    cmd = [sys.executable, "-m", "locust_tpu.serve", "--port", "0"]
    if workers:
        cmd += ["--workers", ",".join(workers),
                "--shard-min-blocks", "1"]
    proc = subprocess.Popen(cmd, env=env, stderr=subprocess.PIPE,
                            text=True)
    line = proc.stderr.readline()
    assert "listening on" in line, line
    host, _, port = line.rsplit(" ", 1)[1].strip().partition(":")
    return proc, host, port

def submit(port, corpus, plan_path, background=False):
    p = subprocess.Popen(
        [sys.executable, "-m", "locust_tpu.serve", "submit",
         corpus, "--plan", plan_path, "--port", port] + cfg_flags,
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    if background:
        return p
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, err[-800:]
    return out

sd, _, sport = spawn_daemon()
try:
    oracle_join = submit(sport, corpus_path, join_path)
    oracle_pr = submit(sport, edges_path, pr_path)
    subprocess.run(
        [sys.executable, "-m", "locust_tpu.serve", "shutdown",
         "--port", sport],
        env=env, capture_output=True, timeout=60,
    )
    sd.wait(timeout=30)
finally:
    if sd.poll() is None:
        sd.kill()

def drill(plan_path, corpus, phase, oracle, kill_after_stages,
          min_stages, match=None):
    wa, aa = spawn_worker()
    wb, ab = spawn_worker(fault={"seed": 7, "rules": [
        {"site": "plan.stage", "action": "delay", "delay_s": 8.0,
         "match": match or {"phase": phase}, "times": 1}]})
    dproc, host, port = spawn_daemon([aa, ab])
    try:
        sub = submit(port, corpus, plan_path, background=True)
        client = ServeClient((host, int(port)), b"dplan-smoke",
                             timeout=60.0)
        deadline = time.time() + 120.0
        while time.time() < deadline:
            try:
                pl = client.stats()["pool"]["plan"]
            except Exception:
                pl = {}
            if pl.get("stages", 0) >= kill_after_stages:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("%s drill never reached %d stages"
                                 % (phase, kill_after_stages))
        time.sleep(0.5)  # the held stage is now in flight on wb
        wb.send_signal(signal.SIGKILL)
        wb.wait(timeout=10)
        out, err = sub.communicate(timeout=240)
        assert sub.returncode == 0, (phase, err[-800:])
        assert out == oracle, (
            "distributed %s plan != solo daemon\\n%r\\n%r"
            % (phase, out[:200], oracle[:200])
        )
        pl = client.stats()["pool"]["plan"]
        assert pl["stages"] >= min_stages, (phase, pl)
        assert pl["recomputes"] >= 1, (phase, pl)
        assert pl["plan_solo_fallbacks"] == 0, (phase, pl)
        client.shutdown()
        dproc.wait(timeout=60)
    finally:
        for p in (wa, wb, dproc):
            if p.poll() is None:
                p.kill()

# Join: the map wave (2 splits) completes, then wb's join stage is held
# open 8s — the SIGKILL lands mid-join-bin and the survivor re-joins
# that bin from the durable leaf partitions.
drill(join_path, corpus_path, "join", oracle_join,
      kill_after_stages=2, min_stages=4)
# Iterate: epoch 1 (2 rank shards) completes and journals, then wb's
# epoch-2 sweep is held open — the SIGKILL lands mid-epoch and the
# survivor recomputes that rank shard from epoch 1's partitions.
drill(pr_path, edges_path, "iterate", oracle_pr,
      kill_after_stages=2, min_stages=6,
      match={"phase": "iterate", "split": 2})
print("[check] dplan smoke ok (join tree + pagerank plans across 2 "
      "real workers; SIGKILL mid-join-stage and mid-pagerank-epoch -> "
      "survivor recompute, byte-identical to the solo daemon)",
      file=sys.stderr)
"""


_FUSED_STREAM_SMOKE = """
import os, subprocess, sys, tempfile

td = tempfile.mkdtemp(prefix="locust_fused_stream_smoke_")
corpus_path = os.path.join(td, "corpus.txt")
with open(corpus_path, "wb") as f:
    f.write((b"alpha beta gamma\\nbeta gamma delta\\nalpha alpha\\n"
             b"epsilon zeta\\n") * 160)   # 640 lines = 20 blocks of 32
cfg_flags = ["--block-lines", "32", "--line-width", "128",
             "--key-width", "16", "--emits-per-line", "8"]
env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": os.getcwd()}

# The oracle: the one-shot hasht CLI over the same corpus + caps.
one_shot = subprocess.run(
    [sys.executable, "-m", "locust_tpu", corpus_path,
     "--backend", "cpu", "--no-timing", "--sort-mode", "hasht"]
    + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert one_shot.returncode == 0, one_shot.stderr[-800:]

# The persistent streaming kernel: `--stream --sort-mode fused` folds
# 8-block segment buffers inside one kernel dispatch each (megakernel
# v2, docs/PERF.md) — 20 blocks = 3 segments, the last PARTIAL, so the
# zero-pad path is inside the identity, not just the aligned case.
fused = subprocess.run(
    [sys.executable, "-m", "locust_tpu", corpus_path,
     "--backend", "cpu", "--no-timing", "--stream",
     "--sort-mode", "fused"] + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert fused.returncode == 0, fused.stderr[-800:]
assert fused.stdout == one_shot.stdout, (
    "streamed fused run != one-shot hasht CLI\\n%r\\n%r"
    % (fused.stdout[:200], one_shot.stdout[:200])
)
# The run must have taken the streaming FORMULATION, not a demotion:
# run_stream surfaces it in the `[locust] stream:` stats line.
assert b"'formulation': 'stream'" in fused.stderr, fused.stderr[-800:]
print("[check] fused-stream smoke ok (persistent streaming kernel, "
      "3 segments incl. a partial, byte-identical to the one-shot "
      "hasht CLI)", file=sys.stderr)
"""


_FAILOVER_SMOKE = """
import json, os, signal, subprocess, sys, tempfile, time

td = tempfile.mkdtemp(prefix="locust_failover_smoke_")
corpus_path = os.path.join(td, "corpus.txt")
with open(corpus_path, "wb") as f:
    f.write(b"alpha beta gamma\\nbeta gamma delta\\n" * 8)
cfg_flags = ["--block-lines", "8", "--line-width", "64",
             "--key-width", "16", "--emits-per-line", "8"]
env = {**os.environ, "JAX_PLATFORMS": "cpu",
       "PYTHONPATH": os.getcwd(), "LOCUST_SECRET": "failover-smoke"}

# The oracles: the one-shot CLIs for the wordcount job AND the
# two-stage tf-idf PLAN job.
one_shot = subprocess.run(
    [sys.executable, "-m", "locust_tpu", corpus_path,
     "--backend", "cpu", "--no-timing"] + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert one_shot.returncode == 0, one_shot.stderr[-800:]
tfidf_shot = subprocess.run(
    [sys.executable, "-m", "locust_tpu", "tfidf", corpus_path,
     "--backend", "cpu", "--lines-per-doc", "2"] + cfg_flags,
    env=env, capture_output=True, timeout=240,
)
assert tfidf_shot.returncode == 0, tfidf_shot.stderr[-800:]

def spawn(extra, env=env):
    proc = subprocess.Popen(
        [sys.executable, "-m", "locust_tpu.serve", "--port", "0"] + extra,
        env=env, stderr=subprocess.PIPE, text=True,
    )
    line = proc.stderr.readline()
    assert "listening on" in line, line
    addr = line.split("listening on ", 1)[1].split(" ")[0].strip()
    host, _, port = addr.partition(":")
    return proc, (host, int(port))

from locust_tpu.plan import tfidf_plan
from locust_tpu.serve.client import ServeClient

SECRET = b"failover-smoke"
sdir, pdir = os.path.join(td, "standby-j"), os.path.join(td, "primary-j")
standby, saddr = spawn(["--journal-dir", sdir,
                        "--standby-of", "127.0.0.1:9"])
primary, paddr = spawn(["--journal-dir", pdir,
                        "--ship-to", f"{saddr[0]}:{saddr[1]}"])
zombie = None
try:
    pc = ServeClient(paddr, SECRET, timeout=30.0)
    sc = ServeClient(saddr, SECRET, timeout=30.0)
    cfgov = {"block_lines": 8, "line_width": 64, "key_width": 16,
             "emits_per_line": 8}
    corpus = open(corpus_path, "rb").read()
    job_id = pc.submit(corpus=corpus, config=cfgov,
                       no_cache=True)["job_id"]
    plan_id = pc.submit(corpus=corpus, config=cfgov,
                        plan=tfidf_plan(2).to_doc(),
                        no_cache=True)["job_id"]
    # Both acks are durable on the primary the instant they return;
    # wait for the async WAL ship to land them on the standby (the
    # operator's replication-lag check), then kill the machine.
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        rep = sc.stats()["replication"]["standby"]
        if rep["applied_seq"] >= 2 and rep["missing_spills"] == 0:
            break
        time.sleep(0.1)
    assert rep["applied_seq"] >= 2 and rep["missing_spills"] == 0, rep
    primary.send_signal(signal.SIGKILL)
    primary.wait(timeout=10)

    # Takeover via the CLI surface.
    promote = subprocess.run(
        [sys.executable, "-m", "locust_tpu.serve", "promote",
         "--port", str(saddr[1])],
        env=env, capture_output=True, timeout=60,
    )
    assert promote.returncode == 0, promote.stderr[-400:]

    res = sc.wait(job_id, timeout=240.0)
    got = b"".join(
        k + b"\\t" + str(v).encode() + b"\\n"
        for k, v in sorted(res["pairs"])
    )
    assert got == one_shot.stdout, (
        "failover wordcount != one-shot CLI\\n%r\\n%r"
        % (got[:200], one_shot.stdout[:200])
    )
    pres = sc.wait(plan_id, timeout=240.0)
    assert pres.get("plan") is True, pres.get("plan")
    assert pres["pairs"][0][0] == tfidf_shot.stdout, (
        "failover plan result != one-shot tfidf CLI\\n%r\\n%r"
        % (pres["pairs"][0][0][:200], tfidf_shot.stdout[:200])
    )

    # The zombie: the old primary's machine comes back on its journal,
    # still shipping at the standby — its first ship is rejected with
    # the structured stale_epoch and it demotes itself.
    zombie, zaddr = spawn(["--journal-dir", pdir,
                           "--ship-to", f"{saddr[0]}:{saddr[1]}"])
    zc = ServeClient(zaddr, SECRET, timeout=30.0)
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        zrep = zc.stats()["replication"]
        if zrep["role"] == "standby":
            break
        time.sleep(0.1)
    assert zrep["role"] == "standby", zrep
    assert zrep["fenced_by"] is not None, zrep
    raw = zc._rpc_one(zaddr, {"cmd": "submit", "corpus_b64": "YQo="})
    assert raw.get("code") == "not_primary", raw
    assert raw.get("primary") == f"{saddr[0]}:{saddr[1]}", raw

    # Roster transparency: a client still pointed at the OLD primary's
    # address reaches the new one through the redirect.
    rc = ServeClient([f"{zaddr[0]}:{zaddr[1]}"], SECRET, timeout=30.0)
    assert rc.stats()["replication"]["role"] == "standby"  # direct hit
    ack = rc.submit(corpus=corpus, config=cfgov)           # redirected
    rres = rc.wait(ack["job_id"], timeout=240.0)
    rgot = b"".join(
        k + b"\\t" + str(v).encode() + b"\\n"
        for k, v in sorted(rres["pairs"])
    )
    assert rgot == one_shot.stdout

    sc.shutdown()
    standby.wait(timeout=30)
    zc.shutdown()
    zombie.wait(timeout=30)
finally:
    for p in (standby, primary, zombie):
        if p is not None and p.poll() is None:
            p.kill()
print("[check] failover smoke ok (primary SIGKILL'd mid-job -> standby "
      "promoted, wordcount AND plan replays byte-identical to the "
      "one-shot CLI; zombie restart fenced stale_epoch -> not_primary)",
      file=sys.stderr)
"""


if __name__ == "__main__":
    raise SystemExit(main())
