"""CPU bench grid: sort_mode x block_lines, post-gather-map re-tune.

The CPU static defaults (bench._PER_BACKEND["cpu"]) were tuned in round 3
BEFORE the backend-conditional map dispatch landed; the gather map shifts
the stage balance, so the block/mode optimum may have moved.  Each cell
is a full driver-path bench run in a child process (identical policy to
the number the driver captures).  Appends one grid row to
artifacts/bench_block_cpu_r4.jsonl.

Usage: python scripts/bench_cpu_grid.py [modes] [blocks]
  e.g. python scripts/bench_cpu_grid.py hash1,hashp2 8192,16384,32768
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    modes = (sys.argv[1] if len(sys.argv) > 1 else "hash1,hashp2,hashp").split(",")
    blocks = [int(b) for b in
              (sys.argv[2] if len(sys.argv) > 2 else "8192,16384,32768").split(",")]
    grid = {}
    for mode in modes:
        for bl in blocks:
            env = {
                **os.environ,
                "PYTHONPATH": REPO,
                "JAX_PLATFORMS": "cpu",
                "LOCUST_BENCH_BACKEND": "cpu",
                "LOCUST_BENCH_SORT_MODE": mode,
                "LOCUST_BENCH_BLOCK_LINES": str(bl),
            }
            r = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py")],
                env=env, capture_output=True, text=True, timeout=600,
            )
            lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
            row = json.loads(lines[-1]) if lines else {"error": r.stderr[-200:]}
            grid[f"{mode}@{bl}"] = {
                "mb_s": row.get("value"), "distinct": row.get("distinct"),
            }
            print(f"[grid] {mode}@{bl}: {row.get('value')} MB/s",
                  file=sys.stderr, flush=True)
    out = {
        "ts": round(time.time(), 1),
        "kind": "cpu_bench_grid",
        "backend": "cpu",
        "corpus": "hamlet-replicated 8MB (driver CPU policy)",
        "grid": grid,
        "note": "post-gather-map re-tune (round 4)",
    }
    path = os.path.join(REPO, "artifacts", "bench_block_cpu_r4.jsonl")
    with open(path, "a") as f:
        f.write(json.dumps(out) + "\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
