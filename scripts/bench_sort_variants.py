"""Micro-bench: sort strategies for the Process stage, on the real device.

Compares (per N rows, 8 key lanes):
  A. lex:    lax.sort with 9 keys (invalid + lanes) + value payload
  B. hash64: lax.sort with 3 keys (invalid, h1, h2) + index payload, gather
             after — using the SHIPPED packing.hash_pair (salted-sum form)

Checksums force full materialization: on remote-TPU links,
block_until_ready alone does not reliably block.
"""

import os
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_comp_cache")

import jax
import jax.numpy as jnp
import numpy as np

from locust_tpu.core import packing

N = int(os.environ.get("N", 393216))
L = 8

rng = np.random.default_rng(0)
lanes = jnp.asarray(
    rng.integers(0, 2**32, size=(N, L), dtype=np.uint64).astype(np.uint32)
)
values = jnp.asarray(rng.integers(0, 100, size=(N,), dtype=np.int32))
valid = jnp.asarray(rng.random(N) < 0.6)


def variant_a(lanes, values, valid):
    invalid = (~valid).astype(jnp.uint32)
    operands = (invalid, *(lanes[:, i] for i in range(L)), values)
    out = jax.lax.sort(operands, num_keys=1 + L)
    return jnp.sum(out[1]) + jnp.sum(out[-1].astype(jnp.uint32))


def variant_b(lanes, values, valid):
    invalid = (~valid).astype(jnp.uint32)
    h1, h2 = packing.hash_pair(lanes)
    idx = jnp.arange(N, dtype=jnp.int32)
    _, _, _, sidx = jax.lax.sort((invalid, h1, h2, idx), num_keys=3)
    return jnp.sum(lanes[sidx, 0]) + jnp.sum(values[sidx].astype(jnp.uint32))


def timeit(fn, *args, reps=5):
    f = jax.jit(fn)
    t0 = time.perf_counter()
    float(f(*args))
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(f(*args))
        best = min(best, time.perf_counter() - t0)
    return compile_s, best * 1e3


for name, fn in [("A_lex9", variant_a), ("B_hash3", variant_b)]:
    c, ms = timeit(fn, lanes, values, valid)
    print(f"{name}: compile={c:.1f}s run={ms:.2f}ms  N={N}")
