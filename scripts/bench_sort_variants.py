"""Micro-bench: sort strategies for the Process stage, on the real device.

Compares (per N rows, 8 key lanes):
  A. current: lax.sort with 9 keys (invalid + lanes) + value payload
  B. hash64: lax.sort with 3 keys (invalid, h1, h2) + index payload, gather after
"""

import os
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_comp_cache")

import jax
import jax.numpy as jnp
import numpy as np

N = int(os.environ.get("N", 393216))
L = 8

rng = np.random.default_rng(0)
lanes = jnp.asarray(rng.integers(0, 2**32, size=(N, L), dtype=np.uint64).astype(np.uint32))
values = jnp.asarray(rng.integers(0, 100, size=(N,), dtype=np.int32))
valid = jnp.asarray(rng.random(N) < 0.6)


def variant_a(lanes, values, valid):
    invalid = (~valid).astype(jnp.uint32)
    operands = (invalid, *(lanes[:, i] for i in range(L)), values)
    out = jax.lax.sort(operands, num_keys=1 + L)
    return out[0], out[1], out[-1]


M1 = jnp.uint32(0x85EBCA6B)
M2 = jnp.uint32(0xC2B2AE35)


def _mix(h):
    h ^= h >> 16
    h *= M1
    h ^= h >> 13
    h *= M2
    h ^= h >> 16
    return h


def hash2(lanes):
    h1 = jnp.uint32(0x9E3779B9)
    h2 = jnp.uint32(0x7F4A7C15)
    for i in range(L):
        h1 = _mix(h1 ^ lanes[:, i] if i else h1 ^ lanes[:, i])
        h2 = _mix((h2 * M1) ^ lanes[:, i])
    return h1, h2


def variant_b(lanes, values, valid):
    invalid = (~valid).astype(jnp.uint32)
    h1, h2 = hash2(lanes)
    idx = jnp.arange(N, dtype=jnp.int32)
    _, _, _, sidx = jax.lax.sort((invalid, h1, h2, idx), num_keys=3)
    return lanes[sidx], values[sidx], valid[sidx]


def timeit(fn, *args, reps=5):
    f = jax.jit(fn)
    t0 = time.perf_counter()
    out = jax.block_until_ready(f(*args))
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(*args))
        best = min(best, time.perf_counter() - t0)
    return compile_s, best * 1e3


for name, fn in [("A_lex9", variant_a), ("B_hash3", variant_b)]:
    c, ms = timeit(fn, lanes, values, valid)
    print(f"{name}: compile={c:.1f}s run={ms:.2f}ms  N={N}")
