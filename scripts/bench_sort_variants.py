"""Micro-bench: sort strategies for the Process stage, on the real device.

Compares (per N rows, 8 key lanes):
  A. lex:    lax.sort with 9 keys (invalid + lanes) + value payload
  B. hash64: lax.sort with 3 keys (invalid, h1, h2) + index payload, gather
             after — using the SHIPPED packing.hash_pair (salted-sum form)
  C. hash64: same 3 keys but rows ride as sort payloads (no gather)

Checksums force full materialization: on remote-TPU links,
block_until_ready alone does not reliably block.

Usage: [N=393216] python scripts/bench_sort_variants.py [--backend auto|cpu|tpu]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from locust_tpu.config import machine_cache_dir  # noqa: E402 - jax-free

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", machine_cache_dir())

N = int(os.environ.get("N", 393216))
L = 8


def variant_a(lanes, values, valid):
    import jax
    import jax.numpy as jnp

    invalid = (~valid).astype(jnp.uint32)
    operands = (invalid, *(lanes[:, i] for i in range(L)), values)
    out = jax.lax.sort(operands, num_keys=1 + L)
    return jnp.sum(out[1]) + jnp.sum(out[-1].astype(jnp.uint32))


def variant_b(lanes, values, valid):
    import jax
    import jax.numpy as jnp

    from locust_tpu.core import packing

    invalid = (~valid).astype(jnp.uint32)
    h1, h2 = packing.hash_pair(lanes)
    idx = jnp.arange(N, dtype=jnp.int32)
    _, _, _, sidx = jax.lax.sort((invalid, h1, h2, idx), num_keys=3)
    return jnp.sum(lanes[sidx, 0]) + jnp.sum(values[sidx].astype(jnp.uint32))


def variant_c(lanes, values, valid):
    """hash keys, but rows ride as sort PAYLOADS (no post-sort gather)."""
    import jax
    import jax.numpy as jnp

    from locust_tpu.core import packing

    invalid = (~valid).astype(jnp.uint32)
    h1, h2 = packing.hash_pair(lanes)
    out = jax.lax.sort(
        (invalid, h1, h2, *(lanes[:, i] for i in range(L)), values),
        num_keys=3,
    )
    return jnp.sum(out[3]) + jnp.sum(out[-1].astype(jnp.uint32))


def variant_d(lanes, values, valid):
    """ONE 32-bit sort key: 31-bit hash, validity in the top bit; gather.

    Collisions between distinct keys rise to ~n^2/2^31 per sort, but the
    engine's segment reduce compares full key lanes at boundaries, so a
    collision only duplicates a table row (re-merged on the next fold or
    in the host finalize) — same safety argument as the 64-bit hash mode
    at ~2x the sort-key bandwidth savings.
    """
    import jax
    import jax.numpy as jnp

    from locust_tpu.core import packing

    h1, _ = packing.hash_pair(lanes)
    key = jnp.where(valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))
    idx = jnp.arange(N, dtype=jnp.int32)
    _, sidx = jax.lax.sort((key, idx), num_keys=1)
    return jnp.sum(lanes[sidx, 0]) + jnp.sum(values[sidx].astype(jnp.uint32))


def variant_e(lanes, values, valid):
    """LSD radix sort (pure XLA): 4x8-bit counting passes over the 32-bit
    folded key — an O(n) alternative to lax.sort's comparison network."""
    import jax.numpy as jnp

    from locust_tpu.core import packing
    from locust_tpu.ops.radix_sort import radix_argsort

    h1, _ = packing.hash_pair(lanes)
    key = jnp.where(valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))
    sidx = radix_argsort(key)
    return jnp.sum(lanes[sidx, 0]) + jnp.sum(values[sidx].astype(jnp.uint32))


def variant_f(lanes, values, valid):
    """radix with 64 buckets x 6 passes: 4x less one-hot traffic per pass
    than 8-bit digits at 1.5x the passes — net ~2.7x less bandwidth."""
    import jax.numpy as jnp

    from locust_tpu.core import packing
    from locust_tpu.ops.radix_sort import radix_argsort

    h1, _ = packing.hash_pair(lanes)
    key = jnp.where(valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))
    sidx = radix_argsort(key, bits=6)
    return jnp.sum(lanes[sidx, 0]) + jnp.sum(values[sidx].astype(jnp.uint32))


def variant_g(lanes, values, valid):
    """2 sort keys + payload-carry: validity folded into the top bit of a
    31-bit primary hash (as variant D), full h2 as tiebreaker — one fewer
    key operand than C at the same grouping guarantee (31+32 tiebreak bits;
    the engine's segment reduce compares full lanes at boundaries anyway)."""
    import jax
    import jax.numpy as jnp

    from locust_tpu.core import packing

    h1, h2 = packing.hash_pair(lanes)
    key = jnp.where(valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))
    out = jax.lax.sort(
        (key, h2, *(lanes[:, i] for i in range(L)), values), num_keys=2
    )
    return jnp.sum(out[2]) + jnp.sum(out[-1].astype(jnp.uint32))


def variant_h(lanes, values, valid):
    """Pallas bitonic tiles (ops/pallas/sort.py): variant D's folded
    single key with variant C's payload carriage, tile-local compare
    passes fused in VMEM — the hand-written kernel the engine exposes as
    sort_mode="bitonic"."""
    import jax
    import jax.numpy as jnp

    from locust_tpu.core import packing
    from locust_tpu.ops.pallas.sort import bitonic_sort

    h1, _ = packing.hash_pair(lanes)
    key = jnp.where(valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))
    interpret = jax.default_backend() != "tpu"
    _, pays = bitonic_sort(
        key,
        tuple(lanes[:, i] for i in range(L)) + (values,),
        interpret=interpret,
    )
    return jnp.sum(pays[0]) + jnp.sum(pays[-1].astype(jnp.uint32))


def variant_i(lanes, values, valid):
    """1 sort key + payload-carry: variant D's folded 31-bit key with
    variant C's payload carriage and no tiebreaker — the minimum-traffic
    lax.sort formulation, exposed by the engine as sort_mode="hashp1"
    (one fewer key operand than G; collision story identical to D)."""
    import jax
    import jax.numpy as jnp

    from locust_tpu.core import packing

    h1, _ = packing.hash_pair(lanes)
    key = jnp.where(valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))
    out = jax.lax.sort(
        (key, *(lanes[:, i] for i in range(L)), values), num_keys=1
    )
    return jnp.sum(out[1]) + jnp.sum(out[-1].astype(jnp.uint32))


def variant_j(lanes, values, valid):
    """SORT-FREE aggregation probe: scatter-add into a hash-bucket table.

    The engine's Process+Reduce exists to produce per-key totals; a hash
    table does that in O(n) single-pass traffic instead of O(n log^2 n)
    sort passes — IF the backend's scatter-with-duplicate-indices is not
    serialized.  This variant times the three primitives such an engine
    mode would be built from, at the real shape:

      * scatter-add of values into table_size buckets (duplicate indices),
      * scatter-max claiming a representative key per bucket,
      * per-row gather-back + compare (the collision-verify pass that
        routes mismatched rows to a tiny sort-based fallback).

    It does NOT produce the engine's exact output (collided rows would
    need the fallback pass); it measures whether the primitives leave the
    sort's measured 0.58s/33.6MB far enough behind to justify building
    that mode.  Recorded like every variant; adoption only ever follows
    an engine-level A/B.
    """
    import jax.numpy as jnp

    from locust_tpu.core import packing

    T = 65536  # resolved_table_size at bench shapes
    h1, h2 = packing.hash_pair(lanes)
    folded = jnp.where(valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))
    bucket = (h1 ^ h2) & jnp.uint32(T - 1)
    counts = jnp.zeros(T, jnp.int32).at[bucket].add(
        jnp.where(valid, values, 0), mode="drop"
    )
    claimed = jnp.zeros(T, jnp.uint32).at[bucket].max(
        jnp.where(valid, folded, jnp.uint32(0)), mode="drop"
    )
    mismatch = valid & (claimed[bucket] != folded)
    return (
        jnp.sum(counts.astype(jnp.uint32))
        + jnp.sum(mismatch.astype(jnp.uint32))
    )


def variant_k(lanes, values, valid):
    """MXU histogram probe: scatter-add spelled as a one-hot matmul.

    PRODUCTIZED (round 6) as ``ops/hash_table.mxu_scatter_add`` behind
    engine sort mode "hasht-mxu" — this probe stays as the cheap
    primitive-level A/B against variant J (the exact engine spelling
    adds value limbs + the hit plane for bit-exactness; the engine-level
    verdict rides opp_resume.AB_SORT_MODES).  Decompose
    the bucket id as ``hi * 512 + lo`` and accumulate
    ``counts2d[h, l] = sum_n value_n * onehot_hi[n, h] * onehot_lo[n, l]``
    — ONE ``[128, n] x [n, 512]`` bf16 contraction on the MXU (~47
    GMACs at sweep shape ~ 0.5 ms of v5e MXU time; one-hot traffic
    ~0.9 GB vs the sort's ~14 GB model).  bf16 one-hot entries and
    sub-256 values are exact; f32 accumulation is exact below 2^24 per
    bucket.  Like J this measures the PRIMITIVE — an engine mode still
    needs the representative-key claim/verify ladder for exactness —
    and adoption only ever follows an engine-level A/B.
    """
    import jax.numpy as jnp

    from locust_tpu.core import packing

    T_HI, T_LO = 128, 512  # 65536 buckets as a [128, 512] grid
    h1, h2 = packing.hash_pair(lanes)
    bucket = ((h1 ^ h2) & jnp.uint32(T_HI * T_LO - 1)).astype(jnp.int32)
    hi = bucket >> 9
    lo = bucket & (T_LO - 1)
    w = jnp.where(valid, values, 0).astype(jnp.bfloat16)
    oh_hi = (
        hi[:, None] == jnp.arange(T_HI, dtype=jnp.int32)[None, :]
    ).astype(jnp.bfloat16)
    oh_lo = (
        lo[:, None] == jnp.arange(T_LO, dtype=jnp.int32)[None, :]
    ).astype(jnp.bfloat16)
    counts2d = jnp.einsum(
        "nh,nl->hl",
        oh_hi * w[:, None],
        oh_lo,
        preferred_element_type=jnp.float32,
    )
    return jnp.sum(counts2d).astype(jnp.uint32)


VARIANTS = [
    ("A_lex9", variant_a),
    ("B_hash3_gather", variant_b),
    ("C_hash3_payload", variant_c),
    ("D_hash1_gather", variant_d),
    ("E_radix4x8", variant_e),
    ("F_radix6x6", variant_f),
    ("G_hash2_payload", variant_g),
    ("H_bitonic_pallas", variant_h),
    ("I_hash1_payload", variant_i),
    ("J_scatter_agg", variant_j),
    ("K_mxu_hist", variant_k),
]


def timeit(fn, *args, reps=5):
    import jax

    f = jax.jit(fn)
    t0 = time.perf_counter()
    float(f(*args))
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(f(*args))
        best = min(best, time.perf_counter() - t0)
    return compile_s, best * 1e3


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="auto", choices=["auto", "cpu", "tpu"])
    args = ap.parse_args()

    from locust_tpu.backend import select_backend

    select_backend(args.backend)
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    lanes = jnp.asarray(
        rng.integers(0, 2**32, size=(N, L), dtype=np.uint64).astype(np.uint32)
    )
    values = jnp.asarray(rng.integers(0, 100, size=(N,), dtype=np.int32))
    valid = jnp.asarray(rng.random(N) < 0.6)

    from locust_tpu.utils import artifacts

    print(f"backend={jax.default_backend()} N={N} L={L}", flush=True)
    results = {}
    # LOCUST_SORT_VARIANTS=B,D,E runs a subset (A_lex9's 9-operand sort
    # takes minutes of XLA compile at bench shapes on TPU; skip it when
    # the tunnel-up window is short).
    sel = os.environ.get("LOCUST_SORT_VARIANTS")
    if sel is None:
        chosen = list(VARIANTS)
    else:
        # Env ORDER is priority order: a flapping tunnel window should
        # spend its first compiles on the variants the caller cares about
        # (the sweep puts the open questions first).  Unknown letters are
        # a loud error — a mistyped selector must not silently consume a
        # scarce window with zero measurements; duplicates dedupe.
        by_letter = {name.split("_")[0]: (name, fn) for name, fn in VARIANTS}
        chosen, bad = [], []
        for s in dict.fromkeys(sel.upper().split(",")):
            (chosen if s in by_letter else bad).append(
                by_letter.get(s, s)
            )
        if bad or not chosen:
            raise SystemExit(
                f"LOCUST_SORT_VARIANTS: unknown variant letter(s) {bad}; "
                f"known: {sorted(by_letter)}"
            )
    force = bool(os.environ.get("LOCUST_ARTIFACT_FORCE"))
    for name, fn in chosen:
        # Error-isolate per variant: an unsupported-lowering failure on one
        # (e.g. a Mosaic rejection of the Pallas variant, measured
        # 2026-07-31: H's compile crash killed B-G's whole window) must
        # not cost the remaining variants' measurements — the error IS the
        # evidence row for that variant.
        try:
            c, ms = timeit(fn, lanes, values, valid)
            results[name] = {"compile_s": round(c, 1), "run_ms": round(ms, 3)}
            print(f"{name}: compile={c:.1f}s run={ms:.2f}ms  N={N}", flush=True)
        except Exception as e:  # noqa: BLE001 — captured as evidence
            results[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
            print(f"{name}: ERROR {type(e).__name__}: {str(e)[:200]}",
                  flush=True)
        # Record after EVERY variant: a window that closes mid-run keeps
        # what it measured (consumers read the latest row of the kind).
        artifacts.record(
            "sort_variants",
            {"n_rows": N, "key_lanes": L, "variants": dict(results),
             "partial": name != chosen[-1][0]},
            force=force,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
